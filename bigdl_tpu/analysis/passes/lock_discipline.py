"""lock-discipline: infer each class's guarded state, flag naked access.

The threaded tiers (telemetry exporters/rings, the serving scheduler,
the data-pipeline prefetchers) follow one convention: a thread-shared
class owns a ``threading.Lock`` and every access to the state that
lock guards happens inside ``with self._lock:``.  The PR 3/4/5 review
rounds each caught a site that forgot — this pass mechanizes the
check.

Inference, per class in the thread-shared packages (``telemetry/``,
``serving/``, ``data/``):

* the class is **thread-shared** iff it assigns a
  ``threading.Lock()`` / ``RLock()`` / ``Condition()`` to a ``self``
  attribute (or stores a lock passed in under a ``*lock``-named attr);
* the **guarded set** is every ``self.X`` read or written inside any
  ``with self.<lock>:`` block of the class — MINUS attributes never
  mutated after ``__init__`` (no assignment, augmented assignment,
  subscript store, or mutator-method call outside the constructor):
  those are immutable configuration a locked block merely happens to
  read, not guarded state;
* a read/write of a guarded attribute OUTSIDE every with-lock block is
  a finding — except in ``__init__``/``__new__`` (construction
  happens-before publication, the standard exemption).

Deliberate lock-free reads (racy-but-monotonic counters, snapshot
fast paths) exist; they carry a pragma or a baseline entry saying WHY
the race is benign — which is exactly the review the convention wants.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from bigdl_tpu.analysis.astutil import SourceTree, call_attr_chain
from bigdl_tpu.analysis.findings import Finding
from bigdl_tpu.analysis.registry import register_pass

RULE = "lock-discipline"

# packages whose classes follow the thread-shared convention
_SCOPES = ("bigdl_tpu/telemetry/", "bigdl_tpu/serving/",
           "bigdl_tpu/data/", "bigdl_tpu/fleet/")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_LOCKY_NAME = re.compile(r"(^|_)(lock|mutex|cond)$")
_EXEMPT_METHODS = {"__init__", "__new__"}

# method calls that mutate the receiver in place (deques, dicts, sets,
# lists) — `self.x.append(...)` is a write to x even though the
# attribute node itself is a Load
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "pop", "popleft", "remove", "discard", "clear", "add",
             "update", "setdefault", "sort", "reverse", "rotate"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = call_attr_chain(node)
    return bool(chain) and chain[-1] in _LOCK_CTORS


def _self_attr(node: ast.AST) -> str:
    """'X' for a ``self.X`` attribute node, else ''."""
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                attr = _self_attr(t)
                if not attr:
                    continue
                if _is_lock_ctor(node.value):
                    out.add(attr)
                elif _LOCKY_NAME.search(attr) \
                        and isinstance(node.value, ast.Name):
                    # e.g. `self._lock = lock` (a shared lock handed in)
                    out.add(attr)
    return out


def _with_holds_lock(node: ast.With, locks: Set[str]) -> bool:
    for item in node.items:
        expr = item.context_expr
        # `with self._lock:` or `with self._lock as ...:`
        if _self_attr(expr) in locks:
            return True
        # `with self._cond:` via a Call like self._lock.acquire() — not
        # the convention here; keep the inference narrow
    return False


class _ClassWalk:
    """Two passes over one class body: collect guarded attrs, then
    flag naked accesses."""

    def __init__(self, tree: SourceTree, src, scope: str,
                 cls: ast.ClassDef, findings: List[Finding]):
        self.tree = tree
        self.src = src
        self.scope = scope
        self.cls = cls
        self.findings = findings
        self.locks = _lock_attrs(cls)
        self.guarded: Set[str] = set()
        self.mutated: Set[str] = set()   # written after __init__

    def run(self) -> None:
        if not self.locks:
            return
        for meth in self.cls.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect(meth, under_lock=False)
                if meth.name not in _EXEMPT_METHODS:
                    self._collect_writes(meth)
        self.guarded -= self.locks
        # immutable configuration (never mutated after __init__) is not
        # guarded state, however often a locked block reads it
        self.guarded &= self.mutated
        if not self.guarded:
            return
        for meth in self.cls.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and meth.name not in _EXEMPT_METHODS:
                self._flag(meth, meth.name, under_lock=False)

    # -- pass 1: guarded set ----------------------------------------------

    def _collect(self, node: ast.AST, under_lock: bool) -> None:
        if isinstance(node, ast.With):
            inner = under_lock or _with_holds_lock(node, self.locks)
            for child in ast.iter_child_nodes(node):
                self._collect(child, inner)
            return
        if under_lock:
            attr = _self_attr(node)
            if attr:
                self.guarded.add(attr)
        for child in ast.iter_child_nodes(node):
            self._collect(child, under_lock)

    def _collect_writes(self, meth: ast.AST) -> None:
        """Attrs mutated outside __init__: plain/aug/subscript stores
        and in-place mutator calls (``self.x.append(...)``)."""
        for node in ast.walk(meth):
            attr = _self_attr(node)
            if attr and isinstance(getattr(node, "ctx", None),
                                   (ast.Store, ast.Del)):
                self.mutated.add(attr)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        a = _self_attr(t.value)
                        if a:
                            self.mutated.add(a)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                a = _self_attr(node.func.value)
                if a:
                    self.mutated.add(a)

    # -- pass 2: naked accesses -------------------------------------------

    def _flag(self, node: ast.AST, meth: str, under_lock: bool) -> None:
        if isinstance(node, ast.With):
            inner = under_lock or _with_holds_lock(node, self.locks)
            for child in ast.iter_child_nodes(node):
                self._flag(child, meth, inner)
            return
        if not under_lock:
            attr = _self_attr(node)
            if attr and attr in self.guarded:
                kind = ("write" if isinstance(getattr(node, "ctx", None),
                                              (ast.Store, ast.Del))
                        else "read")
                self.findings.append(self.tree.finding(
                    RULE, "error", self.src, node.lineno,
                    f"{kind} of {self.cls.name}.{attr} outside the "
                    f"lock: this attribute is accessed under "
                    f"`with self.{sorted(self.locks)[0]}:` elsewhere "
                    f"in the class — take the lock, or pragma with the "
                    f"reason the race is benign",
                    scope=f"{self.scope}.{meth}"))
                return  # one finding per attribute node
        for child in ast.iter_child_nodes(node):
            self._flag(child, meth, under_lock)


# ---------------------------------------------------------------------------
# lock-order: acquisition-order cycle detection
# ---------------------------------------------------------------------------
# Two locks acquired nested in BOTH orders anywhere in the threaded
# tiers is the static deadlock smell: thread A holds X wanting Y while
# thread B holds Y wanting X.  PR-10's SIGTERM fix dodged exactly this
# by moving a queue operation out of signal context by hand; this pass
# makes the next instance a lint error instead of a review catch.
#
# Lock identity is (file, class, attribute) for `with self.X:` —
# the only acquisition spelling the suite recognizes (same deliberate
# narrowness as the guarded-set inference above).  Edges come from
# syntactic nesting: `with self.X:` containing `with self.Y:` adds
# X -> Y.  Cross-function nesting through calls is out of static
# reach; the pass documents that limit rather than guessing.

ORDER_RULE = "lock-order"


def _order_edges(cls_qual: str, cls: ast.ClassDef, locks: Set[str],
                 src, edges: Dict) -> None:
    """Collect (outer_lock -> inner_lock) edges from nested
    with-blocks, remembering one witness site per edge."""

    def walk(node, held):
        if isinstance(node, ast.With):
            acquired = [_self_attr(i.context_expr) for i in node.items
                        if _self_attr(i.context_expr) in locks]
            now = list(held)
            for a in acquired:
                key_a = f"{cls_qual}.{a}"
                for h in now:
                    if h != key_a:
                        edges.setdefault((h, key_a),
                                         (src, node.lineno))
                now = now + [key_a]
            for child in ast.iter_child_nodes(node):
                walk(child, now)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for meth in cls.body:
        if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk(meth, [])


@register_pass(ORDER_RULE,
               doc="a pair of locks acquired nested in both orders "
                   "across the threaded tiers (static deadlock smell)")
def run_order(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    edges: Dict = {}  # (outer, inner) -> (src, witness_line)
    for src in tree:
        if src.tree is None or not src.rel.startswith(_SCOPES):
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                locks = _lock_attrs(node)
                if locks:
                    # file-qualified identity: two same-named classes
                    # in different modules own different locks and
                    # must not be conflated into a phantom cycle
                    _order_edges(f"{src.rel}:{node.name}", node, locks,
                                 src, edges)
    reported = set()
    for (a, b), (src, line) in sorted(edges.items(),
                                      key=lambda kv: kv[0]):
        if (b, a) in edges and frozenset((a, b)) not in reported:
            reported.add(frozenset((a, b)))
            o_src, o_line = edges[(b, a)]
            findings.append(tree.finding(
                ORDER_RULE, "error", src, line,
                f"locks {a} and {b} are acquired nested in BOTH "
                f"orders ({a}->{b} here, {b}->{a} at "
                f"{o_src.rel}:{o_line}) — a cross-thread deadlock "
                f"waiting for its schedule; pick one global order or "
                f"pragma with the reason both sites can never "
                f"contend", scope=a))
    return findings


@register_pass(RULE, doc="reads/writes of lock-guarded attributes "
                         "outside the lock in thread-shared classes")
def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    for src in tree:
        if src.tree is None or not src.rel.startswith(_SCOPES):
            continue
        scopes: List[tuple] = [(src.tree, "")]
        classes: Dict[str, ast.ClassDef] = {}
        while scopes:
            node, scope = scopes.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qual = (f"{scope}.{child.name}" if scope
                            else child.name)
                    classes[qual] = child
                    scopes.append((child, qual))
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = (f"{scope}.{child.name}" if scope
                            else child.name)
                    scopes.append((child, qual))
        for qual in sorted(classes):
            _ClassWalk(tree, src, qual, classes[qual], findings).run()
    return findings
