"""metrics-catalog: telemetry metric/span names vs the documented
catalog (the former ``scripts/metrics_lint.py``, as a registered pass).

Rules (unchanged from the standalone lint — see docs/observability.md):

* metric names snake_case, span names ``/``-separated snake_case;
* one declaration site per metric family (``telemetry/families.py``);
* every registered metric in the docs/observability.md catalog, every
  recorded span in its "Span inventory" table;
* the reverse direction (documented but never registered/recorded) is
  a warning — docs may describe families a gated backend registers
  lazily.

``scripts/metrics_lint.py`` remains as a thin CLI shim over this
module so ``tier1.sh``, the smokes, and ship habits don't change.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from bigdl_tpu.analysis.astutil import SourceTree, call_name, load_tree
from bigdl_tpu.analysis.findings import Finding
from bigdl_tpu.analysis.registry import register_pass

RULE = "metrics-catalog"

_METRIC_FNS = {"counter", "gauge", "histogram"}
_SPAN_FNS = {"span", "record_span"}

_METRIC_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_SPAN_RE = re.compile(r"^[a-z][a-z0-9_]*(/[a-z][a-z0-9_]*)*$")

# a name in backticks is "documented" wherever it appears in the doc
_DOC_NAME_RE = re.compile(r"`([a-z][a-z0-9_/]*)`")


class Site(NamedTuple):
    name: str
    kind: str
    file: str
    line: int


def collect(tree: SourceTree) -> Tuple[List[Site], List[Site]]:
    metrics: List[Site] = []
    spans: List[Site] = []
    for src in tree:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            arg0 = node.args[0]
            if not (isinstance(arg0, ast.Constant)
                    and isinstance(arg0.value, str)):
                continue
            callee = call_name(node)
            if callee in _METRIC_FNS:
                metrics.append(Site(arg0.value, callee, src.rel,
                                    node.lineno))
            elif callee in _SPAN_FNS:
                spans.append(Site(arg0.value, callee, src.rel,
                                  node.lineno))
    return metrics, spans


def documented_names(doc_path: str) -> Set[str]:
    if not os.path.isfile(doc_path):
        return set()
    with open(doc_path, "r", encoding="utf-8") as f:
        return set(_DOC_NAME_RE.findall(f.read()))


def span_inventory(doc_path: str) -> Set[str]:
    """Span names from the doc's "## Span inventory" section — the
    first backticked name of each table row.  The INVENTORY table is
    the contract, not a name incidentally backticked in prose."""
    if not os.path.isfile(doc_path):
        return set()
    with open(doc_path, "r", encoding="utf-8") as f:
        text = f.read()
    out: Set[str] = set()
    in_section = False
    for line in text.splitlines():
        if line.startswith("## "):
            in_section = line.lower().startswith("## span inventory")
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        m = _DOC_NAME_RE.search(line)
        if m and _SPAN_RE.match(m.group(1)):
            out.add(m.group(1))
    return out


@register_pass(RULE, doc="metric/span names vs the docs/observability.md "
                         "catalog: naming, single declaration site, "
                         "both directions")
def run(tree: SourceTree) -> List[Finding]:
    doc_path = os.path.join(tree.repo, "docs", "observability.md")
    doc_rel = "docs/observability.md"
    findings: List[Finding] = []

    def emit(severity: str, file: str, line: int, message: str) -> None:
        src = tree.get(file)
        code = src.code_at(line) if src is not None else ""
        findings.append(Finding(RULE, severity, file, line, message,
                                scope="", code=code))

    metrics, spans = collect(tree)
    docs = documented_names(doc_path)
    inventory = span_inventory(doc_path)
    if not os.path.isfile(doc_path):
        emit("error", doc_rel, 0, f"missing catalog doc {doc_rel}")
    elif not inventory:
        emit("error", doc_rel, 0,
             "docs/observability.md has no parseable 'Span inventory' "
             "table")

    by_name: Dict[str, List[Site]] = {}
    for s in metrics:
        by_name.setdefault(s.name, []).append(s)
        if not _METRIC_RE.match(s.name):
            emit("error", s.file, s.line,
                 f"metric name {s.name!r} is not snake_case")
    for name, sites in sorted(by_name.items()):
        if len(sites) > 1:
            where = ", ".join(f"{s.file}:{s.line}" for s in sites)
            emit("error", sites[0].file, sites[0].line,
                 f"metric {name!r} registered at {len(sites)} sites "
                 f"({where}); declare each family once, in "
                 f"bigdl_tpu/telemetry/families.py")
        if name not in docs:
            s = sites[0]
            emit("error", s.file, s.line,
                 f"metric {name!r} missing from the "
                 f"docs/observability.md catalog")

    seen_spans: Set[str] = set()
    for s in spans:
        if not _SPAN_RE.match(s.name):
            emit("error", s.file, s.line,
                 f"span name {s.name!r} is not snake_case path segments")
        if s.name not in inventory and s.name not in seen_spans:
            emit("error", s.file, s.line,
                 f"span {s.name!r} missing from the "
                 f"docs/observability.md span inventory")
        seen_spans.add(s.name)

    # reverse direction: documented but nothing emits it -> warning
    for name in sorted(inventory - seen_spans):
        emit("warning", doc_rel, 0,
             f"docs/observability.md span inventory lists {name!r} but "
             f"nothing records it")
    for name in sorted(docs - set(by_name)):
        # only names that LOOK like catalog entries (unit/total
        # suffixes); plain prose backticks are not the catalog's problem
        if "/" not in name and re.search(
                r"_(total|seconds|bytes|ms|ratio|depth|max)$", name):
            emit("warning", doc_rel, 0,
                 f"docs/observability.md documents {name!r} but nothing "
                 f"registers it")
    return findings


def lint(root: Optional[str] = None) -> Tuple[List[str], List[str]]:
    """Compat surface for the ``scripts/metrics_lint.py`` shim:
    (errors, warnings) as printable strings, same content the
    standalone lint always printed."""
    tree = load_tree(root)
    errors: List[str] = []
    warnings: List[str] = []
    for f in run(tree):
        text = (f"{f.file}:{f.line}: {f.message}" if f.line
                else f.message)
        (errors if f.severity == "error" else warnings).append(text)
    return errors, warnings
