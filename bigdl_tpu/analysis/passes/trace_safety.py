"""trace-safety: no impure host calls reachable from traced code.

A function traced by ``jax.jit`` / ``shard_map`` / ``pallas_call``
executes its Python ONCE, at trace time; an impure host call inside it
(``time.time()``, ``random``, ``print``, an ``.item()`` host sync)
either bakes a stale value into the compiled program, fires once
instead of per step, or silently blocks the dispatch pipeline on a
device→host transfer.  Every one of these is a bug class a review
round has already caught by eye; this pass walks the call graph so the
next one is caught by machine.

Mechanics (whole-program, AST only — no jax import):

1. **Roots**: in the trace-owning areas (``optim/optimizer.py``,
   ``parallel/``, ``ops/``), any function that is (a) passed to /
   decorated with a tracing transform (``jit``, ``shard_map`` and its
   compat spellings, ``pallas_call``, ``grad``/``value_and_grad``,
   ``vmap``/``pmap``, ``lax.scan``/``fori_loop``/``while_loop``/
   ``cond``/``switch``, ``checkpoint``/``remat``, ``custom_vjp``), or
   (b) uses mapped-axis primitives (``lax.axis_index``, the
   ``telemetry.collectives`` wrappers) — such a function only makes
   sense inside a mapped trace.
2. **Edges**: from each reached function, calls are resolved through
   the module's import tables (module-level and function-local) to
   module-level functions in other ``bigdl_tpu`` modules, to sibling
   functions of the same module, and ``self.method`` to methods of the
   enclosing class.  A root's nested ``def``s are part of its body.
   Dynamic dispatch (``model.forward``, criterion objects, optimizer
   methods) is out of reach by design — those surfaces are covered by
   the compiled-HLO passes instead.
3. **Flags**: host-clock reads, host RNG (``random``/``np.random``),
   ``print``, host syncs (``.item()``, ``np.asarray``/``np.array``,
   ``jax.device_get``, ``.block_until_ready()``), and — in ROOT
   functions only — ``float()``/``int()`` of a parameter (a root's
   parameters are the traced arrays; transitively-reached helpers
   routinely coerce static config the same way, which is fine).

Intentional trace-TIME host work (the collectives wrappers' byte
accounting runs while jax traces, by design) carries a pragma naming
that fact.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from bigdl_tpu.analysis.astutil import (
    SourceTree, call_attr_chain, imports_of,
)
from bigdl_tpu.analysis.findings import Finding
from bigdl_tpu.analysis.registry import register_pass

RULE = "trace-safety"

# areas whose functions can BE trace roots (the known trace entry
# points); edges are followed into any bigdl_tpu module from there
_ROOT_AREAS = ("bigdl_tpu/optim/optimizer.py", "bigdl_tpu/parallel/",
               "bigdl_tpu/ops/")

_TRACE_CALLS = {
    "jit", "pjit", "shard_map", "shard_map_compat", "pallas_call",
    "grad", "value_and_grad", "vmap", "pmap", "scan", "fori_loop",
    "while_loop", "cond", "switch", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "associative_scan",
}
# local aliases of the shard_map compat wrapper seen in the tree
_TRACE_ALIASES = {"_shard_map", "_sm"}

# calling these only makes sense inside a mapped trace -> implicit root
_MAPPED_PRIMS = {"axis_index", "psum", "pmean", "all_gather",
                 "all_to_all", "ppermute", "psum_scatter",
                 "reduce_scatter", "optimization_barrier"}

_HOST_RNG_MODULES = {"random", "np.random", "numpy.random"}
_HOST_SYNC_CALLS = {"asarray", "array", "device_get"}
_HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
_CLOCK_CALLS = {"time", "perf_counter", "monotonic", "process_time"}


class _Func:
    """One function/lambda we may reach: its AST, module, qualname."""

    __slots__ = ("node", "src", "qual", "cls")

    def __init__(self, node, src, qual: str, cls: Optional[str]):
        self.node = node
        self.src = src
        self.qual = qual
        self.cls = cls


class _ModuleIndex:
    """Per-module lookup tables the edge resolver needs."""

    def __init__(self, src):
        self.src = src
        self.mod_alias, self.from_import = imports_of(src.tree)
        self.top: Dict[str, _Func] = {}       # module-level functions
        self.methods: Dict[Tuple[str, str], _Func] = {}
        self.all_funcs: List[_Func] = []
        self._index()

    def _index(self) -> None:
        def walk(body, scope: str, cls: Optional[str]):
            for node in body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    qual = f"{scope}.{node.name}" if scope else node.name
                    fn = _Func(node, self.src, qual, cls)
                    self.all_funcs.append(fn)
                    if not scope:
                        self.top[node.name] = fn
                    if cls is not None:
                        self.methods.setdefault((cls, node.name), fn)
                    walk(node.body, qual, cls)
                elif isinstance(node, ast.ClassDef):
                    qual = f"{scope}.{node.name}" if scope else node.name
                    walk(node.body, qual, node.name)
                elif isinstance(node, (ast.If, ast.Try, ast.With)):
                    walk([c for c in ast.iter_child_nodes(node)
                          if isinstance(c, ast.stmt)], scope, cls)

        walk(self.src.tree.body, "", None)


def _callee_is_tracer(call: ast.Call) -> bool:
    chain = call_attr_chain(call)
    if not chain:
        return False
    last = chain[-1]
    return last in _TRACE_CALLS or last in _TRACE_ALIASES


class _Pass:
    def __init__(self, tree: SourceTree):
        self.tree = tree
        self.findings: List[Finding] = []
        self.modules: Dict[str, _ModuleIndex] = {}
        for src in tree:
            if src.tree is not None \
                    and src.rel.startswith("bigdl_tpu/"):
                self.modules[src.module] = _ModuleIndex(src)
        self.visited: Set[int] = set()    # id(ast node)
        # (func, root label, is_root) — roots are enqueued by
        # find_roots() before walk() adds any transitive callee, so a
        # function that is both reached and a root keeps is_root=True
        self.queue: List[Tuple[_Func, str, bool]] = []

    # -- root discovery ----------------------------------------------------

    def find_roots(self) -> None:
        for mod, idx in self.modules.items():
            if not idx.src.rel.startswith(_ROOT_AREAS):
                continue
            # lexical def environments so `jit(step)` resolves `step`
            # wherever it is nested
            self._scan_scope(idx, idx.src.tree.body, [{}], "")
            # implicit roots: functions using mapped-axis primitives
            for fn in idx.all_funcs:
                if self._uses_mapped_prims(fn.node):
                    self._enqueue(fn, f"{mod}.{fn.qual} (mapped-axis "
                                      f"primitive user)", is_root=True)

    def _scan_scope(self, idx: _ModuleIndex, body, envs: List[Dict],
                    scope: str) -> None:
        # bind this scope's function defs
        env = envs[-1]
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{scope}.{node.name}" if scope else node.name
                env[node.name] = _Func(node, idx.src, qual, None)
        for node in body:
            for call in [n for n in ast.walk(node)
                         if isinstance(n, ast.Call)]:
                if not _callee_is_tracer(call):
                    continue
                for arg in list(call.args) + [kw.value
                                              for kw in call.keywords]:
                    fn = None
                    if isinstance(arg, ast.Lambda):
                        fn = _Func(arg, idx.src,
                                   f"{scope}.<lambda>" if scope
                                   else "<lambda>", None)
                    elif isinstance(arg, ast.Name):
                        for e in reversed(envs):
                            if arg.id in e:
                                fn = e[arg.id]
                                break
                        if fn is None:
                            fn = idx.top.get(arg.id)
                    if fn is not None:
                        self._enqueue(
                            fn, f"{idx.src.module}.{fn.qual} "
                                f"(traced via "
                                f"{call_attr_chain(call)[-1]})",
                            is_root=True)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                qual = f"{scope}.{node.name}" if scope else node.name
                self._scan_scope(idx, node.body, envs + [{}], qual)
            elif isinstance(node, (ast.If, ast.Try, ast.With)):
                self._scan_scope(
                    idx, [c for c in ast.iter_child_nodes(node)
                          if isinstance(c, ast.stmt)], envs, scope)

    def _uses_mapped_prims(self, fnode) -> bool:
        for n in ast.walk(fnode):
            if isinstance(n, ast.Call):
                chain = call_attr_chain(n)
                if chain and chain[-1] in _MAPPED_PRIMS:
                    # skip the trace-size probe psum(1, a)
                    if chain[-1] in ("psum", "pmean") and n.args \
                            and isinstance(n.args[0], ast.Constant):
                        continue
                    return True
        return False

    # -- reachability ------------------------------------------------------

    def _enqueue(self, fn: _Func, root: str,
                 is_root: bool = False) -> None:
        if id(fn.node) in self.visited:
            return
        self.visited.add(id(fn.node))
        self.queue.append((fn, root, is_root))

    def _resolve(self, idx: _ModuleIndex, call: ast.Call,
                 cls: Optional[str]) -> Optional[_Func]:
        f = call.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in idx.top:
                return idx.top[name]
            tgt = idx.from_import.get(name)
            if tgt is not None:
                mod, attr = tgt
                other = self.modules.get(mod)
                if other is not None:
                    return other.top.get(attr)
            return None
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    return idx.methods.get((cls, f.attr))
                mod = idx.mod_alias.get(base.id)
                if mod is not None and mod in self.modules:
                    return self.modules[mod].top.get(f.attr)
                tgt = idx.from_import.get(base.id)
                if tgt is not None:
                    # `from bigdl_tpu.telemetry import collectives as c`
                    dotted = f"{tgt[0]}.{tgt[1]}"
                    if dotted in self.modules:
                        return self.modules[dotted].top.get(f.attr)
        return None

    def walk(self) -> None:
        while self.queue:
            fn, root, is_root = self.queue.pop()
            idx = self.modules.get(fn.src.module)
            if idx is None:
                continue
            self._check_body(fn, root, is_root)
            for n in ast.walk(fn.node):
                if isinstance(n, ast.Call):
                    tgt = self._resolve(idx, n, fn.cls)
                    if tgt is not None:
                        self._enqueue(tgt, root)

    # -- impurity checks ---------------------------------------------------

    def _check_body(self, fn: _Func, root: str, is_root: bool) -> None:
        params: Set[str] = set()
        args = fn.node.args
        for a in (args.args + args.posonlyargs + args.kwonlyargs):
            params.add(a.arg)
        where = (f"in {fn.src.module}.{fn.qual}, reachable from trace "
                 f"root {root}")
        for n in ast.walk(fn.node):
            if not isinstance(n, ast.Call):
                continue
            chain = call_attr_chain(n)
            msg = None
            if chain:
                last = chain[-1]
                dotted = ".".join(chain)
                if len(chain) >= 2 and chain[-2] == "time" \
                        and last in _CLOCK_CALLS:
                    msg = (f"host clock read ({dotted}) inside traced "
                           f"code executes once at trace time, not per "
                           f"step")
                elif any(dotted.startswith(m + ".")
                         for m in _HOST_RNG_MODULES):
                    msg = (f"host RNG ({dotted}) inside traced code is "
                           f"frozen at trace time — use jax.random "
                           f"with a threaded key")
                elif chain == ("print",):
                    msg = ("print() inside traced code fires at trace "
                           "time only — use jax.debug.print for "
                           "per-step output")
                elif last in _HOST_SYNC_CALLS and len(chain) >= 2 \
                        and chain[-2] in ("np", "numpy", "jax", "onp"):
                    msg = (f"{dotted} on a traced value forces a "
                           f"device→host sync (or freezes a tracer at "
                           f"trace time)")
                elif last in _HOST_SYNC_METHODS and len(chain) >= 2 \
                        and chain[-2] not in ("np", "numpy", "random"):
                    msg = (f".{last}() is a device→host sync — inside "
                           f"traced code it blocks the dispatch "
                           f"pipeline (or fails on a tracer)")
                elif is_root and chain in (("float",), ("int",)) \
                        and n.args \
                        and isinstance(n.args[0], ast.Name) \
                        and n.args[0].id in params:
                    msg = (f"{last}() of parameter "
                           f"{n.args[0].id!r} forces a potential "
                           f"tracer to a host scalar")
            if msg:
                self.findings.append(self.tree.finding(
                    RULE, "error", fn.src, n.lineno,
                    f"{msg} ({where})",
                    scope=f"{fn.src.module.split('.', 1)[-1]}"
                          f".{fn.qual}"))


@register_pass(RULE, doc="impure host calls (clock, RNG, print, host "
                         "syncs) reachable from jit/shard_map traced "
                         "functions")
def run(tree: SourceTree) -> List[Finding]:
    p = _Pass(tree)
    p.find_roots()
    p.walk()
    return p.findings
