"""thread-lifecycle: every thread is daemon or joined on shutdown.

The framework starts worker threads in ten-plus places (the loss-drain
worker, serving schedulers, telemetry exporters, prefetchers, the
debugz sidecar).  A non-daemon thread nobody joins keeps the process
alive after ``main`` returns — the classic "training finished but the
job hangs until the scheduler SIGKILLs it" failure, which PR-2's
SIGTERM drain and PR-4's exporter-stop contract each fixed by hand
once.  This pass mechanizes the rule: a ``threading.Thread(...)``
construction must be

* daemon — ``daemon=True`` in the constructor, or ``<obj>.daemon =
  True`` before ``start()`` in the same scope; or
* reachable from a join/stop on the shutdown path — ``self.X.join()``
  anywhere in the owning class for a ``self.X = Thread(...)``
  attribute, or ``x.join()`` in the same function for a local.

Threads that are *both* daemon and joined (the exporter pattern:
daemon so a crash never wedges, joined so a clean stop flushes) are
the gold standard and trivially pass.  Intentional exceptions carry a
pragma with the reason, as everywhere in graftlint.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from bigdl_tpu.analysis.astutil import (SourceTree, call_attr_chain,
                                        imports_of)
from bigdl_tpu.analysis.findings import Finding
from bigdl_tpu.analysis.registry import register_pass

RULE = "thread-lifecycle"


def _is_thread_ctor(node: ast.AST, aliases: tuple) -> bool:
    mod_names, thread_names = aliases
    if not isinstance(node, ast.Call):
        return False
    chain = call_attr_chain(node)
    if len(chain) >= 2 and chain[-1] == "Thread" \
            and chain[-2] in mod_names:
        return True
    return len(chain) == 1 and chain[0] in thread_names


def _thread_aliases(mod_ast: ast.AST) -> tuple:
    """(module names that mean ``threading`` — incl. ``import
    threading as t`` aliases, local names that mean
    ``threading.Thread`` via from-imports)."""
    mods, from_imports = imports_of(mod_ast)
    mod_names = {alias for alias, mod in mods.items()
                 if mod == "threading"} | {"threading"}
    thread_names = {alias for alias, (mod, name) in from_imports.items()
                    if mod == "threading" and name == "Thread"}
    return mod_names, thread_names


def _ctor_daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _self_attr(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


def _joined_or_daemoned(scope: ast.AST, name: str,
                        is_self_attr: bool) -> bool:
    """True when ``<name>.join(...)`` is called or ``<name>.daemon =
    True`` is assigned anywhere inside ``scope`` (the owning class for
    a self attribute, the enclosing function for a local)."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            recv = node.func.value
            if is_self_attr and _self_attr(recv) == name:
                return True
            if not is_self_attr and isinstance(recv, ast.Name) \
                    and recv.id == name:
                return True
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and isinstance(node.value, ast.Constant) \
                        and node.value.value is True:
                    recv = t.value
                    if is_self_attr and _self_attr(recv) == name:
                        return True
                    if not is_self_attr and isinstance(recv, ast.Name) \
                            and recv.id == name:
                        return True
    return False


def _enclosing(stack: List[ast.AST], kinds) -> Optional[ast.AST]:
    for node in reversed(stack):
        if isinstance(node, kinds):
            return node
    return None


def _scope_name(stack: List[ast.AST]) -> str:
    parts = [n.name for n in stack
             if isinstance(n, (ast.ClassDef, ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    return ".".join(parts)


@register_pass(RULE, doc="threading.Thread constructions that are "
                         "neither daemon nor reachable from a "
                         "join/stop on the shutdown path")
def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    for src in tree:
        if src.tree is None:
            continue
        aliases = _thread_aliases(src.tree)
        # walk with an ancestor stack so each ctor knows its
        # assignment target, enclosing function, and enclosing class
        stack: List[ast.AST] = []

        def visit(node):
            stack.append(node)
            ctor = None
            target_attr = target_local = ""
            if isinstance(node, (ast.Assign, ast.AnnAssign,
                                 ast.NamedExpr)) \
                    and node.value is not None \
                    and _is_thread_ctor(node.value, aliases):
                ctor = node.value
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if _self_attr(t):
                        target_attr = _self_attr(t)
                    elif isinstance(t, ast.Name):
                        target_local = t.id
            elif isinstance(node, ast.Call) \
                    and _is_thread_ctor(node, aliases) \
                    and not isinstance(
                        stack[-2] if len(stack) > 1 else None,
                        (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
                ctor = node  # unassigned: Thread(...).start()
            if ctor is not None and not _ctor_daemon_true(ctor):
                ok = False
                func = _enclosing(stack[:-1], (ast.FunctionDef,
                                               ast.AsyncFunctionDef))
                cls = _enclosing(stack[:-1], ast.ClassDef)
                if target_attr and cls is not None:
                    ok = _joined_or_daemoned(cls, target_attr, True)
                elif target_local and func is not None:
                    ok = _joined_or_daemoned(func, target_local, False)
                if not ok:
                    what = (f"self.{target_attr}" if target_attr
                            else target_local or "an unnamed thread")
                    findings.append(tree.finding(
                        RULE, "error", src, ctor.lineno,
                        f"{what} is a non-daemon thread with no "
                        f"reachable join: it will outlive shutdown "
                        f"and wedge process exit — pass daemon=True, "
                        f"or join it on the stop path (or pragma with "
                        f"the reason it is owned elsewhere)",
                        scope=_scope_name(stack)))
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()

        visit(src.tree)
    return findings
