"""collective-discipline: every byte on the wire must be accounted.

PR 7's instrumented wrappers (:mod:`bigdl_tpu.telemetry.collectives`)
exist so that ``collective_bytes_total{op,axis}`` states the true comm
budget of a compiled step.  A raw ``jax.lax.psum`` call site anywhere
else moves bytes that silently vanish from that accounting — the exact
drift the PR-7 review rounds kept re-finding by hand.  Two rules:

* ``collective-discipline``: a raw ``lax.<collective>`` call outside
  ``telemetry/collectives.py``.  Carve-out: ``lax.psum(<const>, axis)``
  — the axis-size probe idiom (``psum(1, a)``) constant-folds at trace
  time and never lowers to a collective, so it moves nothing.
* ``collective-axis``: a string-literal axis name passed to a
  collective (wrapper or raw) that is not one of the canonical mesh
  axes in ``parallel/mesh.AXES`` — a typo'd axis fails at run time
  deep inside a shard_map; a renamed axis silently stops matching.
"""

from __future__ import annotations

import ast
from typing import List

from bigdl_tpu.analysis.astutil import (
    SourceTree, call_attr_chain, mesh_axes,
)
from bigdl_tpu.analysis.findings import Finding
from bigdl_tpu.analysis.registry import register_pass

RULE = "collective-discipline"
AXIS_RULE = "collective-axis"

_COLLECTIVES = {"psum", "pmean", "all_gather", "all_to_all", "ppermute",
                "psum_scatter", "reduce_scatter"}
# the one module allowed to touch jax.lax collectives directly
_HOME = "bigdl_tpu/telemetry/collectives.py"

# positional index of the axis-name argument per collective
_AXIS_ARG = {name: 1 for name in _COLLECTIVES}


def _scope_stack_walk(tree_node: ast.AST):
    """Yield (node, scope) with scope the dotted enclosing qualname."""
    stack: List[tuple] = [(tree_node, "")]
    while stack:
        node, scope = stack.pop()
        yield node, scope
        child_scope = scope
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            child_scope = f"{scope}.{node.name}" if scope else node.name
        for child in ast.iter_child_nodes(node):
            stack.append((child, child_scope))


def _axis_literals(call: ast.Call, name: str) -> List[str]:
    """String-literal axis names passed to a collective call (positional
    or ``axis_name=``); [] when the axis is a variable."""
    node = None
    idx = _AXIS_ARG.get(name)
    if idx is not None and len(call.args) > idx:
        node = call.args[idx]
    for kw in call.keywords:
        if kw.arg == "axis_name":
            node = kw.value
    out: List[str] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
    return out


@register_pass(RULE, doc="raw jax.lax collectives bypassing the "
                         "accounting wrappers; non-canonical axis-name "
                         "literals", rules=(AXIS_RULE,))
def run(tree: SourceTree) -> List[Finding]:
    axes = mesh_axes(tree)
    findings: List[Finding] = []
    for src in tree:
        if src.tree is None:
            continue
        for node, scope in _scope_stack_walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = call_attr_chain(node)
            if not chain or chain[-1] not in _COLLECTIVES:
                continue
            name = chain[-1]
            is_raw = len(chain) >= 2 and chain[-2] == "lax"
            if is_raw and src.rel != _HOME:
                # axis-size probe: psum of a literal constant folds at
                # trace time, no collective is lowered
                if not (name in ("psum", "pmean") and node.args
                        and isinstance(node.args[0], ast.Constant)):
                    findings.append(tree.finding(
                        RULE, "error", src, node.lineno,
                        f"raw jax.lax.{name} bypasses the "
                        f"telemetry.collectives accounting wrappers — "
                        f"its bytes vanish from collective_bytes_total; "
                        f"route it through "
                        f"bigdl_tpu.telemetry.collectives.{name}",
                        scope=scope))
            for axis in _axis_literals(node, name):
                if axis not in axes:
                    findings.append(tree.finding(
                        AXIS_RULE, "error", src, node.lineno,
                        f"axis name {axis!r} passed to {name} is not a "
                        f"canonical mesh axis "
                        f"(parallel/mesh.AXES = {sorted(axes)})",
                        scope=scope))
    return findings
