"""Compiled-HLO lint: invariants of the programs training actually
dispatches.

The AST passes prove source-level discipline; these passes read the
OPTIMIZED HLO of real compiled steps — through the same
``Optimizer.compile_step`` + ``utils/xla_cost`` machinery the comm
tooling uses — and pin the invariants no AST can see:

* ``hlo-cross-slice`` — the single-slice flat-DP step emits ZERO
  collectives classified as crossing slices (the classifier's ground
  truth), and the 2-slice flat baseline emits MORE than zero (the
  classifier is not blind) — together they anchor every ratio below;
* ``hlo-dcn-ratio`` — the hierarchical step's cross-slice payload vs
  the flat fp32 all-reduce baseline stays within the PR-8 acceptance
  envelope (fp32/int8 <= 30%, bf16 <= 55% on the CPU backend, which
  emulates bf16 collectives in f32);
* ``hlo-narrow-wire`` — the permanent regression pin for the PR-8
  widening bug: every dcn-spanning collective of the compressed int8
  step carries its payload in s8 (the f32 residue — per-bucket scales,
  the scalar loss pmean — must stay a small fraction of the crossing
  bytes).  With ``BIGDL_TPU_UNPIN_DCN_WIRE=1`` (the deliberate
  failure-mode seam in ``parallel/hierarchy.py``) this pass MUST flag
  the program — asserted in tests, runnable by hand via
  ``BIGDL_TPU_UNPIN_DCN_WIRE=1 python -m bigdl_tpu.analysis
  --hlo-only --select hlo-narrow-wire`` (must FAIL);
* ``hlo-fast-tier`` — the hierarchical schedule's fast-tier
  reduce-scatter never spans slices (a mesh-layout regression would
  silently put the full-width scatter on the slow wire);
* ``hlo-donation`` — donated input buffers actually elide the
  full-size parameter copy: the entry's ``input_output_alias`` covers
  at least the model's parameter bytes;
* ``hlo-recompile`` — lowering the same step twice yields the same
  program (nondeterministic lowering is per-step recompile risk);
* ``hlo-host-callback`` — the compiled step contains no host
  callbacks, and an ``info`` census of collective/custom-call counts
  per program.

Needs a backend with >= 8 devices (the 2-slice fake-DCN mesh); the CLI
forces the 8-virtual-CPU-device fallback exactly like the test suite.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.analysis.findings import Finding

__all__ = ["ensure_backend", "run_hlo_passes", "narrow_wire_report",
           "donated_alias_bytes", "HLO_RULES"]

HLO_RULES = ("hlo-cross-slice", "hlo-dcn-ratio", "hlo-narrow-wire",
             "hlo-fast-tier", "hlo-donation", "hlo-recompile",
             "hlo-host-callback")

# the PR-8 acceptance envelope (tests/test_hierarchy.py pins the same
# numbers): cross-slice payload vs the flat fp32 all-reduce baseline
_RATIO_BOUNDS = {"fp32": 0.30, "int8": 0.30, "bf16": 0.55}
# f32 residue allowed on the compressed wire: int8 scales are one f32
# per <=512-element bucket, plus the scalar loss pmean — far under a
# quarter of the crossing bytes on any real gradient
_MAX_WIDE_FRACTION = 0.25

_N_DEVICES = 8


def ensure_backend(n_devices: int = _N_DEVICES):
    """Guarantee >= n_devices on a CPU backend (the same
    virtual-device fallback tests/conftest.py uses), returning the jax
    module.  Raises with the XLA_FLAGS recipe when the backend was
    initialized too early to grow."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0),
            f"--xla_force_host_platform_device_count={n_devices}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) >= n_devices:
        return jax
    import jax.extend.backend

    jax.extend.backend.clear_backends()
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"hlo_lint needs {n_devices} devices but the jax backend "
            f"initialized before the device-count flag could land; "
            f"run with XLA_FLAGS=--xla_force_host_platform_device_count"
            f"={n_devices} JAX_PLATFORMS=cpu")
    return jax


def _finding(rule: str, severity: str, program: str,
             message: str) -> Finding:
    """HLO findings anchor on the program, not a source line — the
    ``file`` is the pseudo-path ``<hlo>`` and the baseline identity
    rides (rule, program, invariant)."""
    return Finding(rule, severity, "<hlo>", 0, message,
                   scope=program, code=rule)


class _Programs:
    """Lazy cache of the compiled probe programs (compiles are the
    expensive part; every pass shares one cache)."""

    def __init__(self):
        self.jax = ensure_backend()
        self._cache: Dict[Tuple, object] = {}
        self._meshes: Dict[str, object] = {}

    # -- builders ----------------------------------------------------------

    def _optimizer(self, mesh_axes: Dict[str, int], hierarchical: bool,
                   wire: Optional[str]):
        import numpy as np

        import bigdl_tpu.nn as nn
        from bigdl_tpu.dataset.dataset import Sample
        from bigdl_tpu.optim import Optimizer, SGD
        from bigdl_tpu.parallel.mesh import MeshConfig
        from bigdl_tpu.utils import set_seed

        set_seed(99)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 10), nn.LogSoftMax())
        opt = (Optimizer(model, [Sample(np.zeros(16, np.float32), 1)],
                         nn.ClassNLLCriterion(), batch_size=16)
               .set_optim_method(SGD(0.1))
               .set_mesh(MeshConfig(**mesh_axes)))
        if hierarchical:
            opt.set_gradient_sync(hierarchical=True, wire_dtype=wire)
        return opt, model

    def _mini_batch(self):
        import numpy as np

        from bigdl_tpu.dataset.dataset import MiniBatch

        rng = np.random.default_rng(5)
        return MiniBatch(rng.normal(size=(16, 16)).astype(np.float32),
                         rng.integers(1, 11, size=(16,)).astype(np.int64))

    def compiled(self, kind: str):
        """kind: "flat8" (data=8, single slice), "dcn-flat",
        "dcn-hier-fp32" / "-bf16" / "-int8"."""
        if kind in self._cache:
            return self._cache[kind]
        if kind == "flat8":
            opt, _ = self._optimizer({"data": _N_DEVICES}, False, None)
        elif kind == "dcn-flat":
            opt, _ = self._optimizer({"dcn": 2, "data": -1}, False, None)
        else:
            wire = kind.rsplit("-", 1)[-1]
            opt, _ = self._optimizer({"dcn": 2, "data": -1}, True,
                                     None if wire == "fp32" else wire)
        self._cache[kind] = opt.compile_step(self._mini_batch())
        return self._cache[kind]

    def param_nbytes(self) -> int:
        _, model = self._optimizer({"data": _N_DEVICES}, False, None)
        total = 0
        for leaf in self.jax.tree_util.tree_leaves(model.parameters()):
            total += int(leaf.size) * leaf.dtype.itemsize
        return total

    def slice_map(self, kind: str) -> Dict[int, int]:
        from bigdl_tpu.parallel.hierarchy import dcn_slice_map
        from bigdl_tpu.parallel.mesh import make_mesh

        axes = ({"data": _N_DEVICES} if kind == "flat8"
                else {"dcn": 2, "data": -1})
        key = "flat8" if kind == "flat8" else "dcn"
        if key not in self._meshes:
            self._meshes[key] = make_mesh(
                axes, self.jax.devices()[:_N_DEVICES])
        return dcn_slice_map(self._meshes[key])


# ---------------------------------------------------------------------------
# the individual checks (each returns findings; empty = invariant holds)
# ---------------------------------------------------------------------------

def narrow_wire_report(compiled_or_text, group_of) -> Dict[str, float]:
    """Byte census of the dcn-CROSSING collectives by dtype width:
    ``{"narrow_bytes", "wide_bytes", "total", "wide_fraction"}`` —
    narrow = sub-32-bit payloads (s8, bf16), wide = 32-bit-and-up.
    The narrow-wire invariant is ``wide_fraction <= 0.25`` AND
    ``narrow_bytes > 0``."""
    from bigdl_tpu.utils.xla_cost import (
        cross_group_hlo_lines, shape_tokens_nbytes,
    )

    narrow = wide = 0.0
    for op, shapes, crosses in (cross_group_hlo_lines(
            compiled_or_text, group_of) or []):
        if not crosses:
            continue
        for _dtype, bits, nbytes in shape_tokens_nbytes(shapes):
            if bits < 32:
                narrow += nbytes
            else:
                wide += nbytes
    total = narrow + wide
    return {"narrow_bytes": narrow, "wide_bytes": wide, "total": total,
            "wide_fraction": (wide / total) if total else 0.0}


def _check_cross_slice(progs: _Programs) -> List[Finding]:
    from bigdl_tpu.utils.xla_cost import cross_group_hlo_bytes

    out: List[Finding] = []
    flat8 = cross_group_hlo_bytes(progs.compiled("flat8"),
                                  progs.slice_map("flat8"))
    if flat8 is None:
        out.append(_finding("hlo-cross-slice", "error", "flat8",
                            "compiled module text unavailable"))
    elif flat8["total"] != 0.0:
        out.append(_finding(
            "hlo-cross-slice", "error", "flat8",
            f"the single-slice flat-DP step emits {flat8['total']:.0f} "
            f"bytes of slice-crossing collectives — a single-slice "
            f"program must emit none (classifier ground truth)"))
    base = cross_group_hlo_bytes(progs.compiled("dcn-flat"),
                                 progs.slice_map("dcn-flat"))
    if base is None or base["total"] <= 0.0:
        out.append(_finding(
            "hlo-cross-slice", "error", "dcn-flat",
            "the 2-slice flat baseline shows no cross-slice bytes — "
            "the classifier is blind (dcn axis not in the mesh? "
            "replica-group decoding broken?) and every ratio pin "
            "downstream is vacuous"))
    return out


def _check_dcn_ratio(progs: _Programs) -> List[Finding]:
    from bigdl_tpu.utils.xla_cost import cross_group_hlo_bytes

    out: List[Finding] = []
    sm = progs.slice_map("dcn-flat")
    base = cross_group_hlo_bytes(progs.compiled("dcn-flat"), sm)
    if not base or base["total"] <= 0:
        return out  # hlo-cross-slice already reported the broken base
    ratios = {}
    for wire, bound in sorted(_RATIO_BOUNDS.items()):
        cross = cross_group_hlo_bytes(
            progs.compiled(f"dcn-hier-{wire}"), sm)
        if cross is None:
            out.append(_finding(
                "hlo-dcn-ratio", "error", f"dcn-hier-{wire}",
                "compiled module text unavailable — the ratio pin "
                "cannot be proven"))
            continue
        ratio = cross["total"] / base["total"]
        ratios[wire] = round(ratio, 4)
        if ratio > bound:
            out.append(_finding(
                "hlo-dcn-ratio", "error", f"dcn-hier-{wire}",
                f"cross-slice payload is {ratio:.1%} of the flat fp32 "
                f"baseline (bound {bound:.0%}) — the hierarchical "
                f"schedule regressed ({cross['total']:.0f} / "
                f"{base['total']:.0f} B)"))
    out.append(_finding(
        "hlo-dcn-ratio", "info", "dcn-hier",
        f"cross-slice bytes vs flat baseline: {ratios} "
        f"(bounds {_RATIO_BOUNDS}, baseline {base['total']:.0f} B)"))
    return out


def _check_narrow_wire(progs: _Programs) -> List[Finding]:
    out: List[Finding] = []
    sm = progs.slice_map("dcn-flat")
    rep = narrow_wire_report(progs.compiled("dcn-hier-int8"), sm)
    if rep["narrow_bytes"] <= 0:
        out.append(_finding(
            "hlo-narrow-wire", "error", "dcn-hier-int8",
            f"no sub-32-bit payload crosses the dcn tier — the int8 "
            f"wire has been widened (the PR-8 optimization_barrier pin "
            f"is gone or bypassed); crossing bytes: {rep}"))
    elif rep["wide_fraction"] > _MAX_WIDE_FRACTION:
        out.append(_finding(
            "hlo-narrow-wire", "error", "dcn-hier-int8",
            f"{rep['wide_fraction']:.1%} of the dcn-crossing payload is "
            f"32-bit+ (allowed {_MAX_WIDE_FRACTION:.0%} for scales + "
            f"the scalar loss) — part of the compressed wire widened "
            f"back; crossing bytes: {rep}"))
    # NOTE bf16 is NOT pinned here: the CPU backend emulates bf16
    # collectives in f32 (visible in the HLO itself), so the narrow
    # invariant genuinely does not hold off-TPU — the byte RATIO pin
    # above still bounds the bf16 wire.
    return out


def _check_fast_tier(progs: _Programs) -> List[Finding]:
    from bigdl_tpu.utils.xla_cost import cross_group_hlo_bytes

    out: List[Finding] = []
    sm = progs.slice_map("dcn-flat")
    for wire in sorted(_RATIO_BOUNDS):
        cross = cross_group_hlo_bytes(
            progs.compiled(f"dcn-hier-{wire}"), sm)
        if cross is None:
            out.append(_finding(
                "hlo-fast-tier", "error", f"dcn-hier-{wire}",
                "compiled module text unavailable — the fast-tier "
                "invariant cannot be proven"))
            continue
        rs = cross.get("reduce-scatter", 0.0)
        if rs > 0:
            out.append(_finding(
                "hlo-fast-tier", "error", f"dcn-hier-{wire}",
                f"the fast-tier reduce-scatter spans slices "
                f"({rs:.0f} B cross-slice) — the mesh layout no longer "
                f"keeps the intra-slice stages on ICI"))
    return out


def donated_alias_bytes(text: str) -> Tuple[float, int]:
    """(total bytes of entry parameters aliased to outputs, number of
    aliased parameters) from a compiled module's
    ``input_output_alias`` map + entry layout."""
    m = re.search(r"entry_computation_layout=\{\((.*?)\)->", text,
                  re.DOTALL)
    if m is None:
        return 0.0, 0
    from bigdl_tpu.utils.xla_cost import shape_tokens_nbytes

    param_bytes = [b for _d, _bits, b in shape_tokens_nbytes(m.group(1))]
    am = re.search(r"input_output_alias=\{(.*?)\}, *\w+=", text,
                   re.DOTALL)
    if am is None:
        am = re.search(r"input_output_alias=\{(.*?)\}", text, re.DOTALL)
    if am is None:
        return 0.0, 0
    aliased = {int(g) for g in re.findall(r":\s*\((\d+)", am.group(1))}
    total = sum(b for i, b in enumerate(param_bytes) if i in aliased)
    return total, len(aliased)


def _check_donation(progs: _Programs) -> List[Finding]:
    out: List[Finding] = []
    text = progs.compiled("flat8").as_text()
    need = progs.param_nbytes()
    got, n = donated_alias_bytes(text)
    if got < need:
        out.append(_finding(
            "hlo-donation", "error", "flat8",
            f"donated inputs alias only {got:.0f} B of outputs but the "
            f"model holds {need} B of parameters — the full-size "
            f"parameter copy is NOT elided (donate_argnums dropped? "
            f"aliasing defeated by a layout change?)"))
    else:
        out.append(_finding(
            "hlo-donation", "info", "flat8",
            f"donation OK: {n} aliased buffers cover {got:.0f} B >= "
            f"{need} B of parameters"))
    return out


def _check_recompile(progs: _Programs) -> List[Finding]:
    out: List[Finding] = []
    opt, _ = progs._optimizer({"data": _N_DEVICES}, False, None)
    a = opt.compile_step(progs._mini_batch()).as_text()
    opt2, _ = progs._optimizer({"data": _N_DEVICES}, False, None)
    b = opt2.compile_step(progs._mini_batch()).as_text()
    if a != b:
        out.append(_finding(
            "hlo-recompile", "warning", "flat8",
            "lowering the same step twice produced different HLO — "
            "nondeterministic lowering busts jit caches and shows up "
            "as per-step recompiles in production"))
    return out


def _check_host_callback(progs: _Programs) -> List[Finding]:
    out: List[Finding] = []
    for kind in ("flat8", "dcn-hier-int8"):
        text = progs.compiled(kind).as_text()
        callbacks = len(re.findall(
            r"custom-call[^\n]*callback", text))
        custom = text.count("custom-call")
        colls = sum(text.count(f"{op}(") + text.count(f"{op}-done(")
                    for op in ("all-reduce", "all-gather", "all-to-all",
                               "reduce-scatter", "collective-permute"))
        if callbacks:
            out.append(_finding(
                "hlo-host-callback", "error", kind,
                f"{callbacks} host callback(s) inside the compiled "
                f"step — each one stalls the device on the host every "
                f"iteration"))
        out.append(_finding(
            "hlo-host-callback", "info", kind,
            f"program census: {colls} collective op(s), {custom} "
            f"custom-call(s), {callbacks} host callback(s)"))
    return out


_CHECKS = (_check_cross_slice, _check_dcn_ratio, _check_narrow_wire,
           _check_fast_tier, _check_donation, _check_recompile,
           _check_host_callback)


def run_hlo_passes(select=None) -> List[Finding]:
    """Compile the probe programs and run every HLO check (or the
    subset ``select`` names by rule id)."""
    progs = _Programs()
    findings: List[Finding] = []
    for check in _CHECKS:
        rule = check.__name__.replace("_check_", "hlo-").replace(
            "_", "-")
        if select is not None and rule not in select:
            continue
        findings.extend(check(progs))
    return findings
