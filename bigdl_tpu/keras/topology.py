"""Keras-style Sequential model: compile / fit / evaluate / predict.

Reference: nn/keras/Topology.scala:55 (compile), :89,116 (fit), :269
(Sequential) — the Keras façade that builds the Optimizer internally.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module
from bigdl_tpu.keras.layers import KerasLayer
from bigdl_tpu.optim.methods import OptimMethod, SGD, Adam, Adagrad, \
    Adadelta, Adamax, RMSprop
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import Top1Accuracy, Loss, MAE, \
    ValidationMethod

__all__ = ["Sequential"]


_OPTIMIZERS = {
    "sgd": lambda: SGD(0.01),
    "adam": Adam,
    "adagrad": Adagrad,
    "adadelta": Adadelta,
    "adamax": Adamax,
    "rmsprop": RMSprop,
}

_LOSSES = {
    "categorical_crossentropy": nn.CategoricalCrossEntropy,
    "sparse_categorical_crossentropy": nn.CrossEntropyCriterion,
    "mse": nn.MSECriterion,
    "mean_squared_error": nn.MSECriterion,
    "mae": nn.AbsCriterion,
    "mean_absolute_error": nn.AbsCriterion,
    "binary_crossentropy": nn.BCECriterion,
    "hinge": nn.MarginCriterion,
    "kld": nn.DistKLDivCriterion,
    "poisson": nn.PoissonCriterion,
    "cosine_proximity": nn.CosineProximityCriterion,
}


def _resolve_metric(m, criterion) -> ValidationMethod:
    if isinstance(m, ValidationMethod):
        return m
    table = {"accuracy": Top1Accuracy, "acc": Top1Accuracy, "mae": MAE}
    if m == "loss":
        return Loss(criterion)
    if m not in table:
        raise ValueError(f"unknown metric {m!r}")
    return table[m]()


class Sequential(Module):
    """``Sequential().add(...).compile(...).fit(x, y)``
    (≙ nn/keras/Topology.scala Sequential:269 + KerasModel:55-158)."""

    def __init__(self):
        super().__init__()
        self.layers = nn.Sequential()
        self._compiled = False
        self.criterion = None
        self.optim_method: Optional[OptimMethod] = None
        self.metrics: List[ValidationMethod] = []

    def add(self, layer: Module) -> "Sequential":
        self.layers.add(layer)
        # propagate shapes eagerly when possible (≙ reference add-time
        # shape inference)
        self._propagate_shapes()
        return self

    def _propagate_shapes(self):
        shape = None
        for lay in self.layers.modules():
            if isinstance(lay, KerasLayer):
                if lay.built:
                    shape = lay.output_shape
                elif shape is not None or lay.input_shape is not None:
                    shape = lay.build(shape or lay.input_shape)
                else:
                    return
            else:
                return  # raw nn layer: no static shape inference

    def build(self, input_shape: Sequence[int]):
        """Force-build all layers from a known (batchless) input shape."""
        shape = tuple(input_shape)
        for lay in self.layers.modules():
            if isinstance(lay, KerasLayer):
                shape = lay.build(shape)
            # raw nn modules keep shape unknown; stop inferring but they
            # are already concrete so nothing to build
        return self

    def forward(self, x):
        return self.layers.forward(x)

    def get_output_shape(self) -> Optional[Tuple[int, ...]]:
        mods = self.layers.modules()
        for lay in reversed(mods):
            if isinstance(lay, KerasLayer):
                return lay.output_shape
        return None

    # ---- the Keras training façade -------------------------------------

    def compile(self, optimizer: Union[str, OptimMethod],
                loss, metrics: Optional[Sequence] = None) -> "Sequential":
        """(≙ Topology.scala:55)"""
        if isinstance(optimizer, str):
            key = optimizer.lower()
            if key not in _OPTIMIZERS:
                raise ValueError(f"unknown optimizer {optimizer!r}")
            optimizer = _OPTIMIZERS[key]()
        if isinstance(loss, str):
            key = loss.lower()
            if key not in _LOSSES:
                raise ValueError(f"unknown loss {loss!r}")
            loss = _LOSSES[key]()
        self.optim_method = optimizer
        self.criterion = loss
        self.metrics = [_resolve_metric(m, loss) for m in (metrics or [])]
        self._compiled = True
        return self

    def _to_samples(self, x, y=None):
        from bigdl_tpu.dataset.dataset import Sample
        if y is None:
            return [Sample(np.asarray(f)) for f in x]
        return [Sample(np.asarray(f), np.asarray(t))
                for f, t in zip(x, y)]

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None) -> "Sequential":
        """(≙ Topology.scala:89,116).  ``x`` may be a numpy array (with
        ``y``), a list of Samples, or a DataSet of MiniBatches."""
        if not self._compiled:
            raise RuntimeError("call compile(optimizer, loss) before fit")
        from bigdl_tpu.optim.optimizer import Optimizer

        if isinstance(x, np.ndarray):
            self.build(x.shape[1:])
            data = self._to_samples(x, y)
        else:
            data = x
        kwargs = {"batch_size": batch_size} \
            if not hasattr(data, "data") else {}
        opt = (Optimizer(self, data, self.criterion, **kwargs)
               .set_optim_method(self.optim_method)
               .set_end_when(Trigger.max_epoch(nb_epoch)))
        if validation_data is not None:
            vx, vy = validation_data
            vdata = self._to_samples(vx, vy) \
                if isinstance(vx, np.ndarray) else vx
            methods = self.metrics or [Loss(self.criterion)]
            opt.set_validation(Trigger.every_epoch(), vdata, methods,
                               batch_size=batch_size)
        opt.optimize()
        return self

    def evaluate(self, x, y=None, batch_size: int = 32):
        """(≙ Topology.scala evaluate)"""
        if not self._compiled:
            raise RuntimeError("call compile before evaluate")
        data = self._to_samples(x, y) if isinstance(x, np.ndarray) else x
        methods = self.metrics or [Loss(self.criterion)]
        from bigdl_tpu.optim.predictor import Evaluator
        return Evaluator(self, batch_size).evaluate(data, methods)

    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        data = self._to_samples(x) if isinstance(x, np.ndarray) else x
        from bigdl_tpu.optim.predictor import Predictor
        return np.stack(Predictor(self, batch_size).predict(data))

    def predict_classes(self, x, batch_size: int = 32) -> np.ndarray:
        data = self._to_samples(x) if isinstance(x, np.ndarray) else x
        from bigdl_tpu.optim.predictor import Predictor
        return Predictor(self, batch_size).predict_class(data)
