"""Keras-1.2.2-compatible API (≙ reference nn/keras/ + pyspark keras)."""

from bigdl_tpu.keras.layers import *     # noqa: F401,F403
from bigdl_tpu.keras.topology import Sequential  # noqa: F401
from bigdl_tpu.keras.converter import (  # noqa: F401
    load_keras, load_keras_json, load_keras_hdf5_weights,
    register_keras_def_converter,
)
