"""Keras-1.2.2-compatible API (≙ reference nn/keras/ + pyspark keras)."""

from bigdl_tpu.keras.layers import *     # noqa: F401,F403
from bigdl_tpu.keras.topology import Sequential  # noqa: F401
