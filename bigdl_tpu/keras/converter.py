"""Keras-1.2.2 model converter: JSON definitions + HDF5 weights.

Reference: pyspark/bigdl/keras/converter.py:32-420 (DefinitionLoader /
WeightLoader / LayerConverter — loads real Keras-1.2.2 model JSON and
HDF5 weight files and rebuilds them as BigDL models).  Same capability
here over the ``bigdl_tpu.keras`` layer set.

Supported definitions: Sequential and functional ``Model`` JSON with
the layer classes in ``_DEF_CONVERTERS``.  Both image orderings load:
``dim_ordering="tf"`` (NHWC) builds TPU-native-layout layers, and
``"th"`` (NCHW — the keras-1.x default) builds the same layers with
``data_format="NCHW"`` so the model's tensor layout survives end to
end (feed it NCHW inputs, exactly like keras did).  Supported weights:
Dense, Convolution2D (both kernel layouts), BatchNormalization,
Embedding, and the recurrent family — LSTM/GRU/SimpleRNN per-gate
Keras arrays are repacked into our fused cells (same positional
semantics as the reference's convert_lstm/convert_gru/
convert_simplernn).

Embedding ids follow this framework's 1-based convention: our id
``i + 1`` is Keras index ``i`` (weight rows map directly).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from bigdl_tpu.core.module import Module, Parameter
import bigdl_tpu.keras.layers as KL
from bigdl_tpu.keras.topology import Sequential

__all__ = ["load_keras", "load_keras_json", "load_keras_hdf5_weights",
           "register_keras_def_converter"]


def _dims(seq):
    # None stays None (variable-length dims, e.g. LSTM timesteps);
    # layers that need a concrete value fail where they consume it
    return tuple(None if d is None else int(d) for d in seq)


def _in_shape(cfg: dict):
    bis = cfg.get("batch_input_shape")
    if bis:
        return _dims(bis[1:])
    if cfg.get("input_shape"):
        return _dims(cfg["input_shape"])
    if cfg.get("input_dim"):
        return (int(cfg["input_dim"]),)
    return None


def _ordering(cfg: dict) -> str:
    """Keras-1.2.2 dim_ordering: "tf" (NHWC) or "th" (NCHW — the keras
    1.x DEFAULT).  th models run with data_format="NCHW" layers so
    their tensor layout survives end to end (≙ the reference, which is
    NCHW-native)."""
    ordering = cfg.get("dim_ordering", "tf")
    if ordering not in ("tf", "th"):
        raise ValueError(f"unknown dim_ordering {ordering!r}")
    return ordering


def _dense(cfg):
    return KL.Dense(int(cfg["output_dim"]),
                    activation=cfg.get("activation"),
                    bias=cfg.get("bias", True),
                    input_shape=_in_shape(cfg))


def _activation(cfg):
    return KL.Activation(cfg["activation"], input_shape=_in_shape(cfg))


def _dropout(cfg):
    return KL.Dropout(float(cfg["p"]), input_shape=_in_shape(cfg))


def _flatten(cfg):
    return KL.Flatten(input_shape=_in_shape(cfg))


def _reshape(cfg):
    return KL.Reshape([int(d) for d in cfg["target_shape"]],
                      input_shape=_in_shape(cfg))


def _conv2d(cfg):
    return KL.Convolution2D(
        int(cfg["nb_filter"]), int(cfg["nb_row"]), int(cfg["nb_col"]),
        activation=cfg.get("activation"),
        border_mode=cfg.get("border_mode", "valid"),
        subsample=tuple(cfg.get("subsample", (1, 1))),
        bias=cfg.get("bias", True), dim_ordering=_ordering(cfg),
        input_shape=_in_shape(cfg))


def _pool2d(cls):
    def cv(cfg):
        return cls(pool_size=tuple(cfg.get("pool_size", (2, 2))),
                   strides=(tuple(cfg["strides"]) if cfg.get("strides")
                            else None),
                   border_mode=cfg.get("border_mode", "valid"),
                   dim_ordering=_ordering(cfg),
                   input_shape=_in_shape(cfg))
    return cv


# (GlobalAveragePooling2D uses the generic _cfg_layer with ordering —
# see _DEF_CONVERTERS)


def _bn(cfg):
    mode = cfg.get("mode", 0)
    if mode != 0:
        raise ValueError(f"BatchNormalization mode={mode} not supported "
                         f"(only feature-wise mode 0)")
    # keras-1.2.2 BN has `axis` (th conv nets use axis=1) rather than
    # dim_ordering; axis 1 on 4-D input = channels-first
    axis = int(cfg.get("axis", -1))
    return KL.BatchNormalization(
        epsilon=float(cfg.get("epsilon", 1e-3)),
        momentum=float(cfg.get("momentum", 0.99)),
        dim_ordering="th" if axis == 1 else "tf",
        input_shape=_in_shape(cfg))


def _embedding(cfg):
    return KL.Embedding(int(cfg["input_dim"]), int(cfg["output_dim"]),
                        input_shape=_in_shape(cfg))


def _recurrent(cls):
    def cv(cfg):
        if cfg.get("stateful"):
            raise ValueError(f"{cls.__name__}: stateful=True is not "
                             f"supported (reference parity)")
        if cfg.get("dropout_U"):
            raise ValueError(
                f"{cls.__name__}: dropout_U={cfg['dropout_U']} "
                f"(recurrent-state dropout) is not supported; "
                f"dropout_W maps to the cells' input dropout")
        kw = {}
        # keras-1.x defaults: activation='tanh',
        # inner_activation='hard_sigmoid' — honor what the config says
        # (the reference maps both, converter.py generate_lstm_cell)
        if "activation" in cfg:
            kw["activation"] = cfg["activation"]
        if "inner_activation" in cfg and cls is not KL.SimpleRNN:
            kw["inner_activation"] = cfg["inner_activation"]
        if cfg.get("dropout_W") and cls is not KL.SimpleRNN:
            kw["dropout_w"] = float(cfg["dropout_W"])
        elif cfg.get("dropout_W"):
            raise ValueError("SimpleRNN: dropout_W is not supported")
        return cls(int(cfg["output_dim"]),
                   return_sequences=cfg.get("return_sequences", False),
                   go_backwards=cfg.get("go_backwards", False),
                   input_shape=_in_shape(cfg), **kw)
    return cv


def _bidirectional(cfg):
    sub = cfg["layer"]
    inner = _convert_layer(sub)
    return KL.Bidirectional(
        inner, merge_mode=cfg.get("merge_mode", "concat"),
        input_shape=_in_shape(cfg)
        or _in_shape(sub.get("config", {})))


def _highway(cfg):
    return KL.Highway(activation=cfg.get("activation", "tanh"),
                      input_shape=_in_shape(cfg))


def _merge(cfg):
    return KL.Merge(mode=cfg.get("mode", "sum"),
                    concat_axis=int(cfg.get("concat_axis", -1)))


def _input_layer(cfg):
    shape = _in_shape(cfg)
    if shape is None:
        raise ValueError("InputLayer without batch_input_shape")
    return KL.InputLayer(shape)


def _cfg_layer(cls, *fields, with_ordering: bool = False, **defaults):
    """Converter that maps listed config fields to constructor args."""
    def cv(cfg):
        kwargs = dict(defaults)
        if with_ordering:
            kwargs["dim_ordering"] = _ordering(cfg)
        for f in fields:
            if f in cfg:
                kwargs[f] = cfg[f]
        return cls(input_shape=_in_shape(cfg), **kwargs)
    return cv


def _pool1d(cls):
    def cv(cfg):
        if cfg.get("border_mode", "valid") != "valid":
            raise ValueError(
                f"{cls.__name__}: border_mode="
                f"{cfg.get('border_mode')!r} is not supported "
                f"(only 'valid')")
        return cls(pool_length=int(cfg.get("pool_length", 2)),
                   stride=(int(cfg["stride"]) if cfg.get("stride")
                           else None),
                   input_shape=_in_shape(cfg))
    return cv


def _conv1d(cfg):
    return KL.Convolution1D(
        int(cfg["nb_filter"]), int(cfg["filter_length"]),
        activation=cfg.get("activation"),
        border_mode=cfg.get("border_mode", "valid"),
        subsample_length=int(cfg.get("subsample_length", 1)),
        input_shape=_in_shape(cfg))


def _zero_pad2d(cfg):
    return KL.ZeroPadding2D(tuple(cfg.get("padding", (1, 1))),
                            dim_ordering=_ordering(cfg),
                            input_shape=_in_shape(cfg))


def _upsample2d(cfg):
    return KL.UpSampling2D(tuple(cfg.get("size", (2, 2))),
                           dim_ordering=_ordering(cfg),
                           input_shape=_in_shape(cfg))


def _td_dense(cfg):
    return KL.TimeDistributedDense(
        int(cfg["output_dim"]), activation=cfg.get("activation"),
        input_shape=_in_shape(cfg))


_DEF_CONVERTERS: Dict[str, Callable[[dict], Module]] = {
    "Dense": _dense, "Activation": _activation, "Dropout": _dropout,
    "Flatten": _flatten, "Reshape": _reshape,
    "Convolution2D": _conv2d,
    "MaxPooling2D": _pool2d(KL.MaxPooling2D),
    "AveragePooling2D": _pool2d(KL.AveragePooling2D),
    "GlobalAveragePooling2D": _cfg_layer(
        KL.GlobalAveragePooling2D, with_ordering=True),
    "BatchNormalization": _bn, "Embedding": _embedding,
    "LSTM": _recurrent(KL.LSTM), "GRU": _recurrent(KL.GRU),
    "SimpleRNN": _recurrent(KL.SimpleRNN),
    "Bidirectional": _bidirectional,
    "Highway": _highway, "Merge": _merge, "InputLayer": _input_layer,
    "Convolution1D": _conv1d,
    "MaxPooling1D": _pool1d(KL.MaxPooling1D),
    "AveragePooling1D": _pool1d(KL.AveragePooling1D),
    "GlobalMaxPooling1D": _cfg_layer(KL.GlobalMaxPooling1D),
    "GlobalAveragePooling1D": _cfg_layer(KL.GlobalAveragePooling1D),
    "GlobalMaxPooling2D": _cfg_layer(KL.GlobalMaxPooling2D,
                                     with_ordering=True),
    "ZeroPadding2D": _zero_pad2d, "UpSampling2D": _upsample2d,
    "RepeatVector": _cfg_layer(KL.RepeatVector, "n"),
    "Permute": _cfg_layer(KL.Permute, "dims"),
    "Masking": _cfg_layer(KL.Masking, "mask_value"),
    "TimeDistributedDense": _td_dense,
    "ELU": _cfg_layer(KL.ELU, "alpha"),
    "LeakyReLU": _cfg_layer(KL.LeakyReLU, "alpha"),
    "ThresholdedReLU": _cfg_layer(KL.ThresholdedReLU, "theta"),
    "SpatialDropout2D": _cfg_layer(KL.SpatialDropout2D, "p",
                                   with_ordering=True),
    "GaussianNoise": _cfg_layer(KL.GaussianNoise, "sigma"),
    "GaussianDropout": _cfg_layer(KL.GaussianDropout, "p"),
}


def register_keras_def_converter(class_name: str,
                                 fn: Callable[[dict], Module]) -> None:
    """Register/override a Keras class_name → layer converter
    (≙ the reference's customized-converter hook)."""
    _DEF_CONVERTERS[class_name] = fn


def _convert_layer(spec: dict) -> Module:
    cls = spec["class_name"]
    conv = _DEF_CONVERTERS.get(cls)
    if conv is None:
        raise ValueError(f"no Keras converter for class {cls!r}; "
                         f"register one with "
                         f"register_keras_def_converter")
    layer = conv(spec.get("config", {}))
    name = spec.get("config", {}).get("name") or spec.get("name")
    if name:
        layer.set_name(name)
    return layer


def load_keras_json(source) -> Module:
    """Keras-1.2.2 model JSON (string, dict, or path) → model
    (≙ DefinitionLoader, keras/converter.py)."""
    if isinstance(source, dict):
        spec = source
    elif isinstance(source, str) and source.lstrip().startswith("{"):
        spec = json.loads(source)
    else:
        with open(source) as f:
            spec = json.load(f)
    cls = spec.get("class_name")
    if cls == "Sequential":
        model = Sequential()
        for layer_spec in spec["config"]:
            model.add(_convert_layer(layer_spec))
        return model
    if cls == "Model":
        return _load_functional(spec["config"])
    raise ValueError(f"unsupported top-level Keras class {cls!r}")


def _load_functional(cfg: dict) -> Module:
    """Functional-API graph → nn.Graph via the Node DSL."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.containers import node_of

    layers = {spec["name"]: spec for spec in cfg["layers"]}
    nodes: Dict[str, Any] = {}

    def build(name: str):
        if name in nodes:
            return nodes[name]
        spec = layers[name]
        if spec["class_name"] == "InputLayer":
            gn = nn.Input()
            nodes[name] = gn
            return gn
        inbound = spec.get("inbound_nodes") or []
        prev_names = [ref[0] for ref in inbound[0]] if inbound else []
        prevs = [build(p) for p in prev_names]
        layer = _convert_layer(spec)
        gn = node_of(layer, *prevs)
        nodes[name] = gn
        return gn

    outs = [build(ref[0]) for ref in cfg["output_layers"]]
    # Graph maps forward() arguments positionally: input order must be
    # the model's declared input_layers order, not traversal order
    inputs = [build(ref[0]) for ref in cfg["input_layers"]]
    return nn.Graph(inputs, outs)


# ---- HDF5 weights (≙ WeightLoader) ----------------------------------------

def _h5_layer_weights(h5path: str) -> Dict[str, List[np.ndarray]]:
    import h5py
    out: Dict[str, List[np.ndarray]] = {}
    with h5py.File(h5path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        layer_names = [n.decode() if isinstance(n, bytes) else n
                       for n in root.attrs.get("layer_names", [])]
        for lname in layer_names:
            g = root[lname]
            wnames = [n.decode() if isinstance(n, bytes) else n
                      for n in g.attrs.get("weight_names", [])]
            out[lname] = [np.asarray(g[w]) for w in wnames]
    return out


def _set_dense(layer, w):
    lin = layer.inner
    while not hasattr(lin, "weight"):
        # unwrap containers: Sequential(linear, activation) /
        # TimeDistributed(linear)
        if hasattr(lin, "layers"):
            lin = lin.layers[0]
        elif hasattr(lin, "layer"):
            lin = lin.layer
        else:
            lin = lin.modules()[0]
    lin.weight = Parameter(w[0].T)   # keras (in, out) → ours (out, in)
    if len(w) > 1 and getattr(lin, "bias", None) is not None:
        lin.bias = Parameter(w[1])


def _set_conv(layer, w):
    conv = layer.inner
    if not hasattr(conv, "weight"):
        conv = conv.layers[0] if hasattr(conv, "layers") \
            else conv.modules()[0]
    kw = w[0]
    if kw.ndim != 4:
        raise ValueError(f"Convolution2D weight rank {kw.ndim}")
    # the kernel layout follows the LAYER's dim_ordering, never a shape
    # heuristic (a th Conv2D(3,3,3) on RGB has the same shape either
    # way and would silently load untransposed): th stores
    # (out, in, rows, cols), tf stores HWIO like us
    if getattr(layer, "dim_ordering", "tf") == "th":
        kw = np.transpose(kw, (2, 3, 1, 0))
    if tuple(kw.shape) != tuple(np.asarray(conv.weight.shape)):
        raise ValueError(
            f"Convolution2D weight shape {kw.shape} does not match the "
            f"layer's {tuple(np.asarray(conv.weight.shape))} "
            f"(dim_ordering={getattr(layer, 'dim_ordering', 'tf')!r})")
    conv.weight = Parameter(kw)
    if len(w) > 1 and getattr(conv, "bias", None) is not None:
        conv.bias = Parameter(w[1])


def _set_bn(layer, w):
    bn = layer.inner
    # keras 1.2.2 order: gamma, beta, running_mean, running_std
    bn.weight = Parameter(w[0])
    bn.bias = Parameter(w[1])
    if len(w) > 2:
        bn.running_mean = np.asarray(w[2], np.float32)
    if len(w) > 3:
        bn.running_var = np.asarray(w[3], np.float32)


def _set_embedding(layer, w):
    emb = layer.inner
    emb.weight = Parameter(w[0])


def _rnn_cell(layer):
    """The fused cell inside a built recurrent wrapper — the Recurrent
    module may sit behind Reverse (go_backwards) / Select stages."""
    inner = layer.inner
    for _, m in inner.named_modules():   # yields inner itself first
        if hasattr(m, "cell"):
            return m.cell
    raise ValueError(f"no recurrent cell found inside {layer!r}")


def _lstm_cell_params(w):
    """Keras-1.2.2 LSTM stores 12 per-gate arrays in (i, c, f, o) gate
    groups: [W_i,U_i,b_i, W_c,U_c,b_c, W_f,U_f,b_f, W_o,U_o,b_o]
    (reference repacking: pyspark converter.py convert_lstm).  Our
    fused cell packs columns (i, f, g=c, o); keras keeps (in, out)
    orientation like us, so no transposes."""
    if len(w) != 12:
        raise ValueError(f"LSTM expects 12 weight arrays, got {len(w)}")
    wi, ui, bi, wc, uc, bc, wf, uf, bf, wo, uo, bo = w
    return {"w_input": np.concatenate([wi, wf, wc, wo], axis=1),
            "w_hidden": np.concatenate([ui, uf, uc, uo], axis=1),
            "bias": np.concatenate([bi, bf, bc, bo])}


def _gru_cell_params(w):
    """Keras-1.2.2 GRU: [W_z,U_z,b_z, W_r,U_r,b_r, W_h,U_h,b_h]
    (reference convert_gru reads exactly these positions).  Our cell
    packs (r, z) gates + a separate candidate, like nn/GRU.scala."""
    if len(w) != 9:
        raise ValueError(f"GRU expects 9 weight arrays, got {len(w)}")
    wz, uz, bz, wr, ur, br, wh, uh, bh = w
    return {"w_input": np.concatenate([wr, wz, wh], axis=1),
            "w_hidden": np.concatenate([ur, uz], axis=1),
            "w_candidate": uh,
            "bias": np.concatenate([br, bz, bh])}


def _simplernn_cell_params(w):
    """Keras-1.2.2 SimpleRNN: [W, U, b] (reference convert_simplernn)."""
    if len(w) != 3:
        raise ValueError(
            f"SimpleRNN expects 3 weight arrays, got {len(w)}")
    return {"w_input": w[0], "w_hidden": w[1], "bias": w[2]}


def _apply_cell_params(cell, params):
    for name, value in params.items():
        setattr(cell, name, Parameter(value))


def _set_lstm(layer, w):
    _apply_cell_params(_rnn_cell(layer), _lstm_cell_params(w))


def _set_gru(layer, w):
    _apply_cell_params(_rnn_cell(layer), _gru_cell_params(w))


def _set_simplernn(layer, w):
    _apply_cell_params(_rnn_cell(layer), _simplernn_cell_params(w))


_CELL_PACKERS = {}  # filled after the KL classes are bound below


def _set_bidirectional(layer, w):
    """Keras-1.2.2 Bidirectional: forward weights then backward weights
    (reference convert_bidirectional splits at the midpoint).  Each
    half repacks exactly like the wrapped layer type, into the
    BiRecurrent's fwd/bwd cells."""
    inner = layer.layer
    packer = _CELL_PACKERS.get(type(inner))
    if packer is None:
        raise NotImplementedError(
            f"Bidirectional weight import for "
            f"{type(inner).__name__} is not supported")
    half = len(w) // 2
    bi = layer.inner
    _apply_cell_params(bi.fwd.cell, packer(w[:half]))
    _apply_cell_params(bi.bwd.cell, packer(w[half:]))


_CELL_PACKERS.update({
    KL.LSTM: _lstm_cell_params, KL.GRU: _gru_cell_params,
    KL.SimpleRNN: _simplernn_cell_params,
})

_WEIGHT_SETTERS = {
    KL.Dense: _set_dense, KL.Convolution2D: _set_conv,
    KL.BatchNormalization: _set_bn, KL.Embedding: _set_embedding,
    KL.LSTM: _set_lstm, KL.GRU: _set_gru, KL.SimpleRNN: _set_simplernn,
    KL.TimeDistributedDense: _set_dense,
    KL.Bidirectional: _set_bidirectional,
}


def load_keras_hdf5_weights(model: Module, h5path: str,
                            strict: bool = True) -> Module:
    """Copy Keras-1.2.2 HDF5 weights into a converted model by layer
    name (≙ WeightLoader.load_weights_from_hdf5)."""
    weights = _h5_layer_weights(h5path)
    named = {m.get_name(): m for _, m in model.named_modules()}
    for lname, w in weights.items():
        if not w:
            continue
        layer = named.get(lname)
        if layer is None:
            if strict:
                raise KeyError(f"weight file layer {lname!r} not found "
                               f"in the model")
            continue
        setter = _WEIGHT_SETTERS.get(type(layer))
        if setter is None:
            raise NotImplementedError(
                f"weight import for {type(layer).__name__} "
                f"(layer {lname!r}) is not supported — custom layers "
                f"must be loaded manually")
        if not getattr(layer, "built", True):
            raise RuntimeError(
                f"layer {lname!r} is not built; call model.build("
                f"input_shape) before loading weights")
        setter(layer, [np.asarray(x) for x in w])
    return model


def load_keras(json_path: Optional[str] = None,
               hdf5_path: Optional[str] = None) -> Module:
    """Load a Keras-1.2.2 model: definition from JSON (or from the
    HDF5's ``model_config`` attribute) plus optional HDF5 weights
    (≙ keras/converter.py load_* entry points)."""
    if json_path is None and hdf5_path is None:
        raise ValueError("provide json_path and/or hdf5_path")
    if json_path is None:
        import h5py
        with h5py.File(hdf5_path, "r") as f:
            raw = f.attrs.get("model_config")
            if raw is None:
                raise ValueError(
                    f"{hdf5_path!r} holds no model_config — pass the "
                    f"model JSON explicitly")
            if isinstance(raw, bytes):
                raw = raw.decode("utf-8")
        model = load_keras_json(raw)
    else:
        model = load_keras_json(json_path)
    if hdf5_path is not None:
        load_keras_hdf5_weights(model, hdf5_path)
    return model
