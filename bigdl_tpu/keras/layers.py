"""Keras-1.2.2-compatible layers with shape inference.

Reference: nn/keras/ (71 wrapper files) + nn/abstractnn/InferShape.scala.
Each Keras layer holds its config and lazily builds the underlying
``bigdl_tpu.nn`` module once the input shape is known — at ``add()``
time when an ``input_shape`` was given upstream, else on first forward.
Shapes exclude the batch dimension, Keras-style.  Image layers are NHWC
(TPU-native layout; the reference's keras layers default to NCHW
``dim_ordering="th"``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module

__all__ = [
    "KerasLayer", "InputLayer", "Dense", "Activation", "Dropout",
    "Flatten", "Reshape", "Convolution2D", "MaxPooling2D",
    "AveragePooling2D", "GlobalAveragePooling2D", "BatchNormalization",
    "Embedding", "LSTM", "GRU", "SimpleRNN", "Highway", "Merge",
    "Convolution1D", "MaxPooling1D", "AveragePooling1D",
    "GlobalMaxPooling1D", "GlobalAveragePooling1D", "GlobalMaxPooling2D",
    "ZeroPadding2D", "UpSampling2D", "RepeatVector", "Permute",
    "Masking", "TimeDistributedDense", "Bidirectional", "ELU",
    "LeakyReLU", "ThresholdedReLU", "SpatialDropout2D",
    "GaussianNoise", "GaussianDropout",
]


def _ordering_value(v: str) -> str:
    """Validate a keras-1.2.2 dim_ordering at construction time (the
    same loudness border_mode gets): "tf" = NHWC, "th" = NCHW."""
    if v not in ("tf", "th"):
        raise ValueError(f"unknown dim_ordering {v!r} (use 'tf' or 'th')")
    return v


def _chw(input_shape, th: bool):
    """(channels, height, width) from a batchless 3-D shape in either
    ordering."""
    if th:
        c, h, w = input_shape
    else:
        h, w, c = input_shape
    return c, h, w


def _spatial_out(th: bool, c, h, w):
    """Batchless output shape in the layer's own ordering."""
    return (c, h, w) if th else (h, w, c)


def _activation_module(name: Optional[str]) -> Optional[Module]:
    if name is None or name == "linear":
        return None
    table = {
        "relu": nn.ReLU, "tanh": nn.Tanh, "sigmoid": nn.Sigmoid,
        "softmax": nn.SoftMax, "softplus": nn.SoftPlus,
        "softsign": nn.SoftSign, "hard_sigmoid": nn.HardSigmoid,
        "elu": nn.ELU, "log_softmax": nn.LogSoftMax,
    }
    if name not in table:
        raise ValueError(f"unknown activation {name!r}")
    return table[name]()


class KerasLayer(Module):
    """Base: config + lazy build (≙ nn/keras/KerasLayer.scala wrapping
    InferShape)."""

    def __init__(self, input_shape: Optional[Sequence[int]] = None):
        super().__init__()
        self.input_shape = tuple(input_shape) if input_shape else None
        self.output_shape: Optional[Tuple[int, ...]] = None
        self.built = False

    # subclass contract -----------------------------------------------------
    def build_layer(self, input_shape: Tuple[int, ...]) \
            -> Tuple[Module, Tuple[int, ...]]:
        raise NotImplementedError

    # ----------------------------------------------------------------------
    def build(self, input_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        if self.built:
            return self.output_shape
        self.input_shape = tuple(input_shape)
        self.inner, self.output_shape = self.build_layer(self.input_shape)
        self.built = True
        return self.output_shape

    def forward(self, x):
        if not self.built:
            self.build(tuple(x.shape[1:]))
        return self.inner.forward(x)


class InputLayer(KerasLayer):
    def __init__(self, input_shape: Sequence[int]):
        super().__init__(input_shape)

    def build_layer(self, input_shape):
        return nn.Identity(), input_shape


class Dense(KerasLayer):
    """(≙ nn/keras/Dense.scala)"""

    def __init__(self, output_dim: int, activation: Optional[str] = None,
                 b_regularizer=None, w_regularizer=None, bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def build_layer(self, input_shape):
        lin = nn.Linear(input_shape[-1], self.output_dim,
                        with_bias=self.bias,
                        w_regularizer=self.w_regularizer,
                        b_regularizer=self.b_regularizer)
        act = _activation_module(self.activation)
        mod = lin if act is None else nn.Sequential(lin, act)
        return mod, tuple(input_shape[:-1]) + (self.output_dim,)


class Activation(KerasLayer):
    def __init__(self, activation: str,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.activation = activation

    def build_layer(self, input_shape):
        return _activation_module(self.activation) or nn.Identity(), \
            input_shape


class Dropout(KerasLayer):
    def __init__(self, p: float,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.p = p

    def build_layer(self, input_shape):
        return nn.Dropout(self.p), input_shape


class Flatten(KerasLayer):
    def build_layer(self, input_shape):
        n = int(np.prod(input_shape))
        return nn.Flatten(), (n,)


class Reshape(KerasLayer):
    def __init__(self, target_shape: Sequence[int],
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.target_shape = tuple(target_shape)

    def build_layer(self, input_shape):
        return nn.Reshape(self.target_shape), self.target_shape


class Convolution2D(KerasLayer):
    """2-D conv (≙ nn/keras/Convolution2D.scala).  dim_ordering "tf":
    input_shape = (rows, cols, channels); "th": (channels, rows, cols)
    — the underlying conv runs data_format="NCHW" so th models keep
    their tensor layout end to end."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1),
                 bias: bool = True, dim_ordering: str = "tf",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"unsupported border_mode {border_mode!r}")
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = subsample
        self.bias = bias
        self.dim_ordering = _ordering_value(dim_ordering)

    def build_layer(self, input_shape):
        th = self.dim_ordering == "th"
        c, h, w = _chw(input_shape, th)
        if self.border_mode == "same":
            # true SAME padding (pad=-1) keeps inference and execution in
            # agreement for even kernels / odd dims
            pad_h = pad_w = -1
            out_h = -(-h // self.subsample[0])
            out_w = -(-w // self.subsample[1])
        else:
            pad_h = pad_w = 0
            out_h = (h - self.nb_row) // self.subsample[0] + 1
            out_w = (w - self.nb_col) // self.subsample[1] + 1
        conv = nn.SpatialConvolution(
            c, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], pad_w, pad_h,
            with_bias=self.bias,
            data_format="NCHW" if th else "NHWC")
        act = _activation_module(self.activation)
        mod = conv if act is None else nn.Sequential(conv, act)
        return mod, _spatial_out(th, self.nb_filter, out_h, out_w)


class _Pooling2D(KerasLayer):
    pool_cls = None

    def __init__(self, pool_size: Tuple[int, int] = (2, 2),
                 strides: Optional[Tuple[int, int]] = None,
                 border_mode: str = "valid", dim_ordering: str = "tf",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.pool_size = pool_size
        self.strides = strides or pool_size
        self.border_mode = border_mode
        self.dim_ordering = _ordering_value(dim_ordering)

    def build_layer(self, input_shape):
        th = self.dim_ordering == "th"
        c, h, w = _chw(input_shape, th)
        pad_h = pad_w = 0
        if self.border_mode == "same":
            out_h = -(-h // self.strides[0])
            out_w = -(-w // self.strides[1])
            pad_h = pad_w = -1  # true SAME padding in the nn layer
        else:
            out_h = (h - self.pool_size[0]) // self.strides[0] + 1
            out_w = (w - self.pool_size[1]) // self.strides[1] + 1
        pool = self.pool_cls(
            self.pool_size[1], self.pool_size[0],
            self.strides[1], self.strides[0], pad_w, pad_h,
            data_format="NCHW" if th else "NHWC")
        return pool, _spatial_out(th, c, out_h, out_w)


class MaxPooling2D(_Pooling2D):
    pool_cls = nn.SpatialMaxPooling


class AveragePooling2D(_Pooling2D):
    pool_cls = nn.SpatialAveragePooling


class GlobalAveragePooling2D(KerasLayer):
    def __init__(self, dim_ordering: str = "tf",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.dim_ordering = _ordering_value(dim_ordering)

    def build_layer(self, input_shape):
        th = self.dim_ordering == "th"
        c, _, _ = _chw(input_shape, th)
        fmt = "NCHW" if th else "NHWC"
        return nn.GlobalAveragePooling2D(data_format=fmt), (c,)


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 dim_ordering: str = "tf",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.epsilon = epsilon
        self.momentum = momentum
        self.dim_ordering = _ordering_value(dim_ordering)

    def build_layer(self, input_shape):
        th = self.dim_ordering == "th"
        if len(input_shape) == 3:
            c = input_shape[0] if th else input_shape[-1]
            bn = nn.SpatialBatchNormalization(
                c, eps=self.epsilon, momentum=1 - self.momentum,
                data_format="NCHW" if th else "NHWC")
        else:
            bn = nn.BatchNormalization(
                input_shape[-1], eps=self.epsilon,
                momentum=1 - self.momentum)
        return bn, input_shape


class Embedding(KerasLayer):
    """(≙ nn/keras/Embedding.scala).  Input: [seq_len] of 1-based ids."""

    def __init__(self, input_dim: int, output_dim: int,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def build_layer(self, input_shape):
        emb = nn.LookupTable(self.input_dim, self.output_dim)
        return emb, tuple(input_shape) + (self.output_dim,)


class _RecurrentLayer(KerasLayer):
    """Keras-1.x recurrent defaults: activation='tanh',
    inner_activation='hard_sigmoid' (NOT plain sigmoid); go_backwards
    prepends Reverse on the time axis (reference
    pyspark converter.py __process_recurrent_layer:885-895)."""

    def __init__(self, output_dim: int, return_sequences: bool = False,
                 activation: Optional[str] = "tanh",
                 inner_activation: Optional[str] = "hard_sigmoid",
                 go_backwards: bool = False, dropout_w: float = 0.0,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.output_dim = output_dim
        self.return_sequences = return_sequences
        self.activation = activation
        self.inner_activation = inner_activation
        self.go_backwards = go_backwards
        self.dropout_w = float(dropout_w)

    @staticmethod
    def _act(name):
        """Explicit activation module: 'linear' must become Identity,
        not the cell's tanh/sigmoid default (None means default)."""
        mod = _activation_module(name)
        return nn.Identity() if mod is None else mod

    def make_cell(self, input_size):
        raise NotImplementedError

    def build_layer(self, input_shape):
        seq_len, feat = input_shape
        rec = nn.Recurrent(self.make_cell(feat))
        stages = ([nn.Reverse(2)] if self.go_backwards else []) + [rec]
        if not self.return_sequences:
            stages.append(nn.Select(2, -1))
        mod = stages[0] if len(stages) == 1 else nn.Sequential(*stages)
        out = (seq_len, self.output_dim) if self.return_sequences \
            else (self.output_dim,)
        return mod, out


class LSTM(_RecurrentLayer):
    def make_cell(self, input_size):
        return nn.LSTM(input_size, self.output_dim, p=self.dropout_w,
                       activation=self._act(self.activation),
                       inner_activation=self._act(self.inner_activation))


class GRU(_RecurrentLayer):
    def make_cell(self, input_size):
        return nn.GRU(input_size, self.output_dim, p=self.dropout_w,
                      activation=self._act(self.activation),
                      inner_activation=self._act(self.inner_activation))


class SimpleRNN(_RecurrentLayer):
    def make_cell(self, input_size):
        return nn.RnnCell(input_size, self.output_dim,
                          self._act(self.activation))


class Highway(KerasLayer):
    def __init__(self, activation: Optional[str] = "tanh",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.activation = activation

    def build_layer(self, input_shape):
        act = _activation_module(self.activation)
        return nn.Highway(input_shape[-1], activation=act), input_shape


class Convolution1D(KerasLayer):
    """(≙ nn/keras/Convolution1D.scala).  Input (steps, features)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None,
                 border_mode: str = "valid", subsample_length: int = 1,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        if border_mode != "valid":
            raise ValueError("Convolution1D supports border_mode='valid'")
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample_length = subsample_length

    def build_layer(self, input_shape):
        steps, feat = input_shape
        conv = nn.TemporalConvolution(feat, self.nb_filter,
                                      self.filter_length,
                                      self.subsample_length)
        act = _activation_module(self.activation)
        mod = conv if act is None else nn.Sequential(conv, act)
        out_steps = None if steps is None else \
            (steps - self.filter_length) // self.subsample_length + 1
        return mod, (out_steps, self.nb_filter)


class MaxPooling1D(KerasLayer):
    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.pool_length = pool_length
        self.stride = stride or pool_length

    def build_layer(self, input_shape):
        steps, feat = input_shape
        out = None if steps is None else \
            (steps - self.pool_length) // self.stride + 1
        return nn.TemporalMaxPooling(self.pool_length, self.stride), \
            (out, feat)


class AveragePooling1D(MaxPooling1D):
    def build_layer(self, input_shape):
        steps, feat = input_shape
        out = None if steps is None else \
            (steps - self.pool_length) // self.stride + 1
        pool = nn.Sequential(
            nn.Unsqueeze(2), nn.SpatialAveragePooling(
                self.pool_length, 1, self.stride, 1,
                data_format="NHWC"), nn.Squeeze(2))
        return pool, (out, feat)


class GlobalMaxPooling1D(KerasLayer):
    def build_layer(self, input_shape):
        return nn.Max(2), (input_shape[-1],)


class GlobalAveragePooling1D(KerasLayer):
    def build_layer(self, input_shape):
        return nn.Mean(2), (input_shape[-1],)


class GlobalMaxPooling2D(KerasLayer):
    def __init__(self, dim_ordering: str = "tf",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.dim_ordering = _ordering_value(dim_ordering)

    def build_layer(self, input_shape):
        th = self.dim_ordering == "th"
        c, _, _ = _chw(input_shape, th)
        # NCHW: max over the two trailing spatial dims; NHWC: dims 2,3
        dim = 3 if th else 2
        return nn.Sequential(nn.Max(dim), nn.Max(dim)), (c,)


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding: Tuple[int, int] = (1, 1),
                 dim_ordering: str = "tf",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.padding = tuple(padding)
        self.dim_ordering = _ordering_value(dim_ordering)

    def build_layer(self, input_shape):
        th = self.dim_ordering == "th"
        c, h, w = _chw(input_shape, th)
        ph, pw = self.padding
        pad = nn.SpatialZeroPadding(
            pw, pw, ph, ph, data_format="NCHW" if th else "NHWC")
        out_h = None if h is None else h + 2 * ph
        out_w = None if w is None else w + 2 * pw
        return pad, _spatial_out(th, c, out_h, out_w)


class UpSampling2D(KerasLayer):
    def __init__(self, size: Tuple[int, int] = (2, 2),
                 dim_ordering: str = "tf",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.size = tuple(size)
        self.dim_ordering = _ordering_value(dim_ordering)

    def build_layer(self, input_shape):
        th = self.dim_ordering == "th"
        c, h, w = _chw(input_shape, th)
        up = nn.UpSampling2D(self.size,
                             data_format="NCHW" if th else "NHWC")
        out_h = None if h is None else h * self.size[0]
        out_w = None if w is None else w * self.size[1]
        return up, _spatial_out(th, c, out_h, out_w)


class RepeatVector(KerasLayer):
    """(≙ nn/keras/RepeatVector.scala): (features,) → (n, features)."""

    def __init__(self, n: int, input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.n = n

    def build_layer(self, input_shape):
        # dim=2: replicate after the batch axis (1-based batched dims)
        return nn.Replicate(self.n, dim=2), (self.n,) + tuple(input_shape)


class Permute(KerasLayer):
    """Permute non-batch dims; Keras 1-based ``dims``."""

    def __init__(self, dims: Sequence[int],
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.dims = tuple(dims)

    def build_layer(self, input_shape):
        # express the permutation as swaps for nn.Transpose (whose pairs
        # are 1-based over the BATCHED array; non-batch pos k ↔ k+1)
        order = [0] + list(self.dims)   # order[pos] = source dim at pos
        cur = list(range(len(order)))   # cur[pos] = source currently there
        pairs = []
        for pos in range(1, len(order)):
            j = cur.index(order[pos])
            if j != pos:
                pairs.append((pos + 1, j + 1))
                cur[pos], cur[j] = cur[j], cur[pos]
        tr = nn.Transpose(pairs) if pairs else nn.Identity()
        return tr, tuple(input_shape[d - 1] for d in self.dims)


class Masking(KerasLayer):
    def __init__(self, mask_value: float = 0.0,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.mask_value = mask_value

    def build_layer(self, input_shape):
        return nn.Masking(self.mask_value), input_shape


class TimeDistributedDense(KerasLayer):
    """(≙ nn/keras TimeDistributed(Dense)): Dense at every timestep."""

    def __init__(self, output_dim: int, activation: Optional[str] = None,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.output_dim = output_dim
        self.activation = activation

    def build_layer(self, input_shape):
        steps, feat = input_shape
        lin = nn.Linear(feat, self.output_dim)
        act = _activation_module(self.activation)
        inner = lin if act is None else nn.Sequential(lin, act)
        return nn.TimeDistributed(inner), (steps, self.output_dim)


class Bidirectional(KerasLayer):
    """Wrap an LSTM/GRU/SimpleRNN layer bidirectionally
    (≙ nn/keras/Bidirectional.scala); merge_mode concat or sum."""

    def __init__(self, layer: "_RecurrentLayer",
                 merge_mode: str = "concat",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape or layer.input_shape)
        if merge_mode not in ("concat", "sum"):
            raise ValueError(f"unsupported merge_mode {merge_mode!r}")
        self.layer = layer
        self.merge_mode = merge_mode

    def build_layer(self, input_shape):
        seq_len, feat = input_shape
        if not self.layer.return_sequences:
            raise ValueError(
                "Bidirectional requires return_sequences=True")
        if getattr(self.layer, "go_backwards", False):
            # keras flips go_backwards for the backward copy; honoring
            # it would swap the halves — reject rather than silently
            # diverge (same policy as stateful/dropout_U)
            raise ValueError(
                "Bidirectional over go_backwards=True is not supported")
        merge = (nn.JoinTable(3) if self.merge_mode == "concat"
                 else nn.CAddTable())
        rec = nn.BiRecurrent(merge=merge,
                             cell=self.layer.make_cell(feat))
        out_dim = (self.layer.output_dim * 2
                   if self.merge_mode == "concat"
                   else self.layer.output_dim)
        return rec, (seq_len, out_dim)


class ELU(KerasLayer):
    def __init__(self, alpha: float = 1.0,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.alpha = alpha

    def build_layer(self, input_shape):
        return nn.ELU(self.alpha), input_shape


class LeakyReLU(KerasLayer):
    def __init__(self, alpha: float = 0.3,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.alpha = alpha

    def build_layer(self, input_shape):
        return nn.LeakyReLU(self.alpha), input_shape


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta: float = 1.0,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.theta = theta

    def build_layer(self, input_shape):
        return nn.Threshold(self.theta, 0.0), input_shape


class SpatialDropout2D(KerasLayer):
    def __init__(self, p: float = 0.5, dim_ordering: str = "tf",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.p = p
        self.dim_ordering = _ordering_value(dim_ordering)

    def build_layer(self, input_shape):
        fmt = "NCHW" if self.dim_ordering == "th" else "NHWC"
        return nn.SpatialDropout2D(self.p, data_format=fmt), input_shape


class GaussianNoise(KerasLayer):
    def __init__(self, sigma: float,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.sigma = sigma

    def build_layer(self, input_shape):
        return nn.GaussianNoise(self.sigma), input_shape


class GaussianDropout(KerasLayer):
    def __init__(self, p: float,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.p = p

    def build_layer(self, input_shape):
        return nn.GaussianDropout(self.p), input_shape


class Merge(KerasLayer):
    """Merge a list of inputs (≙ nn/keras/Merge.scala): mode in
    {sum, mul, max, ave, concat}."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        if mode not in ("sum", "mul", "max", "ave", "concat"):
            raise ValueError(f"unsupported merge mode {mode!r}")
        self.mode = mode
        self.concat_axis = concat_axis

    def build_layer(self, input_shape):
        table = {"sum": nn.CAddTable, "mul": nn.CMulTable,
                 "max": nn.CMaxTable, "ave": nn.CAveTable}
        if self.mode == "concat":
            ndim = len(input_shape) + 1  # batched rank
            dim = (self.concat_axis + 1 if self.concat_axis >= 0
                   else ndim + self.concat_axis + 1)  # 1-based
            # output shape along the concat axis depends on sibling
            # inputs unknown here; leave it as the first input's shape
            return nn.JoinTable(dim), input_shape
        return table[self.mode](), input_shape

    def forward(self, x):
        if not self.built:
            first = x[0] if isinstance(x, (tuple, list)) else x
            self.build(tuple(first.shape[1:]))
        return self.inner.forward(x)


# --------------------------------------------------------------------------
# 3-D / atrous / separable / locally-connected convolution family
# --------------------------------------------------------------------------

def _conv_out(size: int, k: int, s: int, same: bool) -> int:
    return -(-size // s) if same else (size - k) // s + 1


class Convolution3D(KerasLayer):
    """NDHWC 3-D conv (≙ nn/keras/Convolution3D.scala; input_shape =
    (dim1, dim2, dim3, channels))."""

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, activation: Optional[str] = None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int, int] = (1, 1, 1),
                 bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"unsupported border_mode {border_mode!r}")
        self.nb_filter = nb_filter
        self.kernel = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.activation = activation
        self.same = border_mode == "same"
        self.subsample = subsample
        self.bias = bias

    def build_layer(self, input_shape):
        d, h, w, c = input_shape
        k1, k2, k3 = self.kernel
        s1, s2, s3 = self.subsample
        pad = -1 if self.same else 0
        conv = nn.VolumetricConvolution(
            c, self.nb_filter, k1, k3, k2, s1, s3, s2,
            pad_t=pad, pad_w=pad, pad_h=pad, with_bias=self.bias,
            data_format="NDHWC")
        act = _activation_module(self.activation)
        mod = conv if act is None else nn.Sequential(conv, act)
        out = (_conv_out(d, k1, s1, self.same),
               _conv_out(h, k2, s2, self.same),
               _conv_out(w, k3, s3, self.same), self.nb_filter)
        return mod, out


class _Pooling3D(KerasLayer):
    pool_cls: type = None

    def __init__(self, pool_size: Tuple[int, int, int] = (2, 2, 2),
                 strides: Optional[Tuple[int, int, int]] = None,
                 border_mode: str = "valid",
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        if border_mode != "valid":
            raise ValueError("3D pooling supports border_mode='valid'")
        self.pool_size = pool_size
        self.strides = strides or pool_size

    def build_layer(self, input_shape):
        d, h, w, c = input_shape
        k1, k2, k3 = self.pool_size
        s1, s2, s3 = self.strides
        pool = self.pool_cls(k1, k3, k2, s1, s3, s2)
        out = ((d - k1) // s1 + 1, (h - k2) // s2 + 1,
               (w - k3) // s3 + 1, c)
        return pool, out


class MaxPooling3D(_Pooling3D):
    pool_cls = nn.VolumetricMaxPooling


class AveragePooling3D(_Pooling3D):
    pool_cls = nn.VolumetricAveragePooling


class GlobalAveragePooling3D(KerasLayer):
    def build_layer(self, input_shape):
        return nn.GlobalAveragePooling3D(), (input_shape[-1],)


class GlobalMaxPooling3D(KerasLayer):
    def build_layer(self, input_shape):
        return nn.GlobalMaxPooling3D(), (input_shape[-1],)


class AtrousConvolution2D(KerasLayer):
    """Dilated conv (≙ nn/keras/AtrousConvolution2D.scala), NHWC."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 atrous_rate: Tuple[int, int] = (1, 1),
                 activation: Optional[str] = None,
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.rate = atrous_rate
        self.activation = activation
        self.subsample = subsample
        self.bias = bias

    def build_layer(self, input_shape):
        h, w, c = input_shape
        conv = nn.SpatialDilatedConvolution(
            c, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], 0, 0,
            self.rate[1], self.rate[0], data_format="NHWC",
            with_bias=self.bias)
        act = _activation_module(self.activation)
        mod = conv if act is None else nn.Sequential(conv, act)
        eff_r = (self.nb_row - 1) * self.rate[0] + 1
        eff_c = (self.nb_col - 1) * self.rate[1] + 1
        out = ((h - eff_r) // self.subsample[0] + 1,
               (w - eff_c) // self.subsample[1] + 1, self.nb_filter)
        return mod, out


class AtrousConvolution1D(KerasLayer):
    """Dilated temporal conv (≙ nn/keras/AtrousConvolution1D.scala):
    lowered onto the 2-D dilated conv with a singleton width."""

    def __init__(self, nb_filter: int, filter_length: int,
                 atrous_rate: int = 1, activation: Optional[str] = None,
                 subsample_length: int = 1, bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.rate = atrous_rate
        self.activation = activation
        self.subsample = subsample_length
        self.bias = bias

    def build_layer(self, input_shape):
        steps, dim = input_shape
        conv = nn.SpatialDilatedConvolution(
            dim, self.nb_filter, 1, self.filter_length,
            1, self.subsample, 0, 0, 1, self.rate,
            data_format="NHWC", with_bias=self.bias)
        inner = nn.Sequential(
            nn.Reshape((steps, 1, dim)), conv)
        eff = (self.filter_length - 1) * self.rate + 1
        out_steps = (steps - eff) // self.subsample + 1
        inner.add(nn.Reshape((out_steps, self.nb_filter)))
        act = _activation_module(self.activation)
        if act is not None:
            inner.add(act)
        return inner, (out_steps, self.nb_filter)


class SeparableConvolution2D(KerasLayer):
    """Depthwise-separable conv (≙ nn/keras/SeparableConvolution2D.scala)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 depth_multiplier: int = 1,
                 activation: Optional[str] = None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"unsupported border_mode {border_mode!r}")
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.depth_multiplier = depth_multiplier
        self.activation = activation
        self.same = border_mode == "same"
        self.subsample = subsample
        self.bias = bias

    def build_layer(self, input_shape):
        h, w, c = input_shape
        pad = -1 if self.same else 0
        conv = nn.SpatialSeparableConvolution(
            c, self.nb_filter, self.depth_multiplier,
            self.nb_col, self.nb_row, self.subsample[1],
            self.subsample[0], pad, pad, has_bias=self.bias,
            data_format="NHWC")
        act = _activation_module(self.activation)
        mod = conv if act is None else nn.Sequential(conv, act)
        out = (_conv_out(h, self.nb_row, self.subsample[0], self.same),
               _conv_out(w, self.nb_col, self.subsample[1], self.same),
               self.nb_filter)
        return mod, out


class Deconvolution2D(KerasLayer):
    """Transposed conv (≙ nn/keras/Deconvolution2D.scala), NHWC."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None,
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.subsample = subsample
        self.bias = bias

    def build_layer(self, input_shape):
        h, w, c = input_shape
        conv = nn.SpatialFullConvolution(
            c, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0],
            no_bias=not self.bias, data_format="NHWC")
        act = _activation_module(self.activation)
        mod = conv if act is None else nn.Sequential(conv, act)
        out = ((h - 1) * self.subsample[0] + self.nb_row,
               (w - 1) * self.subsample[1] + self.nb_col, self.nb_filter)
        return mod, out


class LocallyConnected1D(KerasLayer):
    """(≙ nn/keras/LocallyConnected1D.scala)"""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None,
                 subsample_length: int = 1, bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample = subsample_length
        self.bias = bias

    def build_layer(self, input_shape):
        steps, dim = input_shape
        lc = nn.LocallyConnected1D(
            steps, dim, self.nb_filter, self.filter_length,
            self.subsample, with_bias=self.bias)
        act = _activation_module(self.activation)
        mod = lc if act is None else nn.Sequential(lc, act)
        out_steps = (steps - self.filter_length) // self.subsample + 1
        return mod, (out_steps, self.nb_filter)


class LocallyConnected2D(KerasLayer):
    """(≙ nn/keras/LocallyConnected2D.scala), NHWC."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None,
                 subsample: Tuple[int, int] = (1, 1), bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.subsample = subsample
        self.bias = bias

    def build_layer(self, input_shape):
        h, w, c = input_shape
        lc = nn.LocallyConnected2D(
            c, w, h, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], with_bias=self.bias,
            data_format="NHWC")
        act = _activation_module(self.activation)
        mod = lc if act is None else nn.Sequential(lc, act)
        out = ((h - self.nb_row) // self.subsample[0] + 1,
               (w - self.nb_col) // self.subsample[1] + 1, self.nb_filter)
        return mod, out


# --------------------------------------------------------------------------
# cropping / padding / upsampling / dropout / misc
# --------------------------------------------------------------------------

class _JnpOp(Module):
    """Private elementwise/jnp-backed helper for thin keras wrappers."""

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        return self._fn(x)


class Cropping1D(KerasLayer):
    def __init__(self, cropping: Tuple[int, int] = (1, 1),
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.cropping = cropping

    def build_layer(self, input_shape):
        steps, dim = input_shape
        l, r = self.cropping
        mod = _JnpOp(lambda x: x[:, l:x.shape[1] - r, :])
        return mod, (steps - l - r, dim)


class Cropping2D(KerasLayer):
    def __init__(self, cropping=((0, 0), (0, 0)),
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.cropping = cropping

    def build_layer(self, input_shape):
        h, w, c = input_shape
        (t, b), (l, r) = self.cropping
        return nn.Cropping2D((t, b), (l, r), data_format="NHWC"), \
            (h - t - b, w - l - r, c)


class Cropping3D(KerasLayer):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)),
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.cropping = cropping

    def build_layer(self, input_shape):
        d, h, w, c = input_shape
        c1, c2, c3 = self.cropping
        return nn.Cropping3D(c1, c2, c3, data_format="NDHWC"), \
            (d - sum(c1), h - sum(c2), w - sum(c3), c)


class ZeroPadding1D(KerasLayer):
    def __init__(self, padding: int = 1,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.padding = padding

    def build_layer(self, input_shape):
        steps, dim = input_shape
        p = self.padding
        mod = _JnpOp(lambda x: jnp.pad(x, ((0, 0), (p, p), (0, 0))))
        return mod, (steps + 2 * p, dim)


class ZeroPadding3D(KerasLayer):
    def __init__(self, padding: Tuple[int, int, int] = (1, 1, 1),
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.padding = padding

    def build_layer(self, input_shape):
        d, h, w, c = input_shape
        p1, p2, p3 = self.padding
        mod = _JnpOp(lambda x: jnp.pad(
            x, ((0, 0), (p1, p1), (p2, p2), (p3, p3), (0, 0))))
        return mod, (d + 2 * p1, h + 2 * p2, w + 2 * p3, c)


class UpSampling1D(KerasLayer):
    def __init__(self, length: int = 2,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.length = length

    def build_layer(self, input_shape):
        steps, dim = input_shape
        return nn.UpSampling1D(self.length), (steps * self.length, dim)


class UpSampling3D(KerasLayer):
    def __init__(self, size: Tuple[int, int, int] = (2, 2, 2),
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.size = size

    def build_layer(self, input_shape):
        d, h, w, c = input_shape
        s1, s2, s3 = self.size
        return nn.UpSampling3D(self.size), (d * s1, h * s2, w * s3, c)


class SpatialDropout1D(KerasLayer):
    def __init__(self, p: float = 0.5,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.p = p

    def build_layer(self, input_shape):
        return nn.SpatialDropout1D(self.p), input_shape


class SpatialDropout3D(KerasLayer):
    def __init__(self, p: float = 0.5,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.p = p

    def build_layer(self, input_shape):
        return nn.SpatialDropout3D(self.p, data_format="NHWC"), \
            input_shape


class MaxoutDense(KerasLayer):
    """(≙ nn/keras/MaxoutDense.scala over nn.Maxout)"""

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 bias: bool = True,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.bias = bias

    def build_layer(self, input_shape):
        return nn.Maxout(input_shape[-1], self.output_dim,
                         self.nb_feature, with_bias=self.bias), \
            (self.output_dim,)


class SReLU(KerasLayer):
    """(≙ nn/keras/SReLU.scala)"""

    def build_layer(self, input_shape):
        return nn.SReLU(input_shape), input_shape


class SoftMax(KerasLayer):
    """(≙ nn/keras/SoftMax.scala)"""

    def build_layer(self, input_shape):
        return nn.SoftMax(), input_shape


class TimeDistributed(KerasLayer):
    """Apply an inner keras layer to every time step
    (≙ nn/keras/TimeDistributed.scala)."""

    def __init__(self, layer: KerasLayer,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        # plain-object slot: assigning a Module attribute would register
        # the layer as a submodule HERE as well as inside the built
        # nn.TimeDistributed, duplicating every parameter in the pytree
        object.__setattr__(self, "_wrapped", layer)

    def build_layer(self, input_shape):
        step_shape = tuple(input_shape[1:])
        out_step = self._wrapped.build(step_shape)
        return nn.TimeDistributed(self._wrapped), \
            (input_shape[0],) + tuple(out_step)


class ConvLSTM2D(KerasLayer):
    """Convolutional LSTM over [time, rows, cols, channels]
    (≙ nn/keras/ConvLSTM2D.scala on nn.ConvLSTMPeephole).  Square
    kernels only, SAME padding; returns the full sequence when
    ``return_sequences`` else the last step."""

    def __init__(self, nb_filter: int, nb_kernel: int,
                 subsample: int = 1, return_sequences: bool = False,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__(input_shape)
        self.nb_filter = nb_filter
        self.nb_kernel = nb_kernel
        self.subsample = subsample
        self.return_sequences = return_sequences

    def build_layer(self, input_shape):
        t, h, w, c = input_shape
        cell = nn.ConvLSTMPeephole(
            c, self.nb_filter, self.nb_kernel, self.nb_kernel,
            stride=self.subsample)
        rec = nn.Recurrent(cell)
        oh = -(-h // self.subsample)
        ow = -(-w // self.subsample)
        if self.return_sequences:
            return rec, (t, oh, ow, self.nb_filter)
        mod = nn.Sequential(rec, _JnpOp(lambda x: x[:, -1]))
        return mod, (oh, ow, self.nb_filter)


__all__ += [
    "Convolution3D", "MaxPooling3D", "AveragePooling3D",
    "GlobalAveragePooling3D", "GlobalMaxPooling3D",
    "AtrousConvolution1D", "AtrousConvolution2D",
    "SeparableConvolution2D", "Deconvolution2D",
    "LocallyConnected1D", "LocallyConnected2D",
    "Cropping1D", "Cropping2D", "Cropping3D",
    "ZeroPadding1D", "ZeroPadding3D", "UpSampling1D", "UpSampling3D",
    "SpatialDropout1D", "SpatialDropout3D", "MaxoutDense", "SReLU",
    "SoftMax", "TimeDistributed", "ConvLSTM2D",
]
