"""Vision pipeline: ImageFeature/ImageFrame + augmentation transformers.

Reference: transform/vision/image/ImageFrame.scala:80-214,
ImageFeature.scala:36, FeatureTransformer.scala, and augmentation/
(Brightness, ChannelNormalize, ChannelOrder, ChannelScaledNormalizer,
ColorJitter, Contrast, Crop, Expand, Filler, HFlip, Hue,
PixelNormalizer, RandomAlterAspect, RandomCropper, RandomResize,
RandomTransformer, Resize, Saturation, ScaleResize), plus the ROI label
transformers (label/roi/*) and MatToTensor/ImageFrameToSample.

TPU-first design: these are *host-side input transforms* (numpy + PIL
replacing the reference's OpenCV JNI) — on TPU the goal is zero host
compute inside the jitted step, so all augmentation happens in the
input pipeline before device transfer, producing ready NHWC float
arrays.  Images are HWC float32 (BGR by default, matching the
reference's OpenCV heritage; ChannelOrder converts).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.dataset import Sample
from bigdl_tpu.dataset.transformer import Transformer

__all__ = [
    "ImageFeature", "ImageFrame", "LocalImageFrame", "FeatureTransformer",
    "Brightness", "ChannelNormalize", "ChannelOrder",
    "ChannelScaledNormalizer", "ColorJitter", "Contrast", "CenterCrop",
    "RandomCrop", "FixedCrop", "Expand", "Filler", "HFlip", "Hue",
    "PixelNormalizer", "RandomAlterAspect", "RandomCropper",
    "RandomResize", "RandomTransformer", "Resize", "Saturation",
    "ScaleResize", "AspectScale", "MatToTensor", "ImageFrameToSample",
    "RoiNormalize", "RoiHFlip", "RoiResize",
]


class ImageFeature(dict):
    """Mutable map describing one image through the pipeline
    (reference ImageFeature.scala:36): standard keys below, arbitrary
    extras allowed.  The working image lives under ``floats`` as an
    HWC float32 numpy array."""

    # standard keys (reference ImageFeature companion object)
    bytes_key = "bytes"
    floats = "floats"
    label = "label"
    uri = "uri"
    original_size = "originalSize"
    bounding_box = "boundingBox"
    size = "size"

    def __init__(self, image: Optional[np.ndarray] = None, label=None,
                 uri: Optional[str] = None, **kw):
        super().__init__(**kw)
        if image is not None:
            img = np.asarray(image, np.float32)
            self[self.floats] = img
            self[self.original_size] = img.shape
        if label is not None:
            self[self.label] = label
        if uri is not None:
            self[self.uri] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.floats]

    @image.setter
    def image(self, v):
        self[self.floats] = np.asarray(v, np.float32)

    def get_label(self):
        return self.get(self.label)

    def width(self) -> int:
        return self.image.shape[1]

    def height(self) -> int:
        return self.image.shape[0]


class ImageFrame:
    """Collection of ImageFeatures (reference ImageFrame.scala:80).
    ``ImageFrame.read`` loads a directory/file via PIL (replacing the
    OpenCV imread path); distributed-frame semantics are covered by
    per-host sharding in the data pipeline (DataSet.shard)."""

    @staticmethod
    def read(path: str, with_label_from_dirs: bool = False) \
            -> "LocalImageFrame":
        from PIL import Image as PILImage
        feats = []
        if os.path.isdir(path):
            if with_label_from_dirs:
                classes = sorted(d for d in os.listdir(path)
                                 if os.path.isdir(os.path.join(path, d)))
                for ci, cls in enumerate(classes):
                    cdir = os.path.join(path, cls)
                    for f in sorted(os.listdir(cdir)):
                        fp = os.path.join(cdir, f)
                        img = np.asarray(PILImage.open(fp).convert("RGB"),
                                         np.float32)[:, :, ::-1]  # BGR
                        feats.append(ImageFeature(img, label=float(ci + 1),
                                                  uri=fp))
            else:
                for f in sorted(os.listdir(path)):
                    fp = os.path.join(path, f)
                    if not os.path.isfile(fp):
                        continue
                    img = np.asarray(PILImage.open(fp).convert("RGB"),
                                     np.float32)[:, :, ::-1]
                    feats.append(ImageFeature(img, uri=fp))
        else:
            img = np.asarray(PILImage.open(path).convert("RGB"),
                             np.float32)[:, :, ::-1]
            feats.append(ImageFeature(img, uri=path))
        return LocalImageFrame(feats)

    @staticmethod
    def from_arrays(images: Sequence[np.ndarray], labels=None) \
            -> "LocalImageFrame":
        labels = labels if labels is not None else [None] * len(images)
        return LocalImageFrame([ImageFeature(im, label=l)
                                for im, l in zip(images, labels)])


class LocalImageFrame(ImageFrame):
    """Array-backed frame (reference LocalImageFrame)."""

    def __init__(self, features: List[ImageFeature]):
        self.features = list(features)

    def __len__(self):
        return len(self.features)

    def __iter__(self):
        return iter(self.features)

    def transform(self, transformer: "FeatureTransformer") \
            -> "LocalImageFrame":
        return LocalImageFrame(list(transformer(iter(self.features))))

    def to_samples(self) -> List[Sample]:
        return [Sample(f.image, f.get_label()) for f in self.features]


class FeatureTransformer(Transformer):
    """Per-image transformer (reference FeatureTransformer.scala):
    subclasses implement ``transform(feature)`` mutating/returning the
    ImageFeature; composition via ``>>`` is inherited."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError

    def apply(self, it):
        for f in it:
            yield self.transform(f)

    def __call__(self, arg):
        if isinstance(arg, ImageFeature):
            return self.transform(arg)
        if isinstance(arg, ImageFrame):
            return arg.transform(self)
        return self.apply(arg)


# --------------------------------------------------------------------------
# pixel-level transforms
# --------------------------------------------------------------------------

class Brightness(FeatureTransformer):
    """Add a uniform delta in [delta_low, delta_high]
    (reference augmentation/Brightness.scala)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0,
                 rng: Optional[np.random.RandomState] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        f.image = f.image + self.rng.uniform(self.low, self.high)
        return f


class Contrast(FeatureTransformer):
    """Scale pixel values by a random factor
    (reference augmentation/Contrast.scala)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 rng: Optional[np.random.RandomState] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        f.image = f.image * self.rng.uniform(self.low, self.high)
        return f


def _bgr_to_hsv(img):
    import colorsys  # noqa: F401  (documentation: vectorized below)
    b, g, r = img[..., 0] / 255.0, img[..., 1] / 255.0, img[..., 2] / 255.0
    mx = np.maximum(np.maximum(r, g), b)
    mn = np.minimum(np.minimum(r, g), b)
    diff = mx - mn
    h = np.zeros_like(mx)
    mask = diff > 1e-12
    rc = np.where(mask, (mx - r) / np.where(mask, diff, 1), 0)
    gc = np.where(mask, (mx - g) / np.where(mask, diff, 1), 0)
    bc = np.where(mask, (mx - b) / np.where(mask, diff, 1), 0)
    h = np.where(mx == r, bc - gc, h)
    h = np.where((mx == g) & mask, 2.0 + rc - bc, h)
    h = np.where((mx == b) & mask, 4.0 + gc - rc, h)
    h = (h / 6.0) % 1.0
    s = np.where(mx > 1e-12, diff / np.where(mx > 1e-12, mx, 1), 0)
    return h, s, mx


def _hsv_to_bgr(h, s, v):
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * fr)
    t = v * (1 - s * (1 - fr))
    i = i.astype(np.int32) % 6
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return np.stack([b, g, r], axis=-1) * 255.0


class Saturation(FeatureTransformer):
    """Scale HSV saturation (reference augmentation/Saturation.scala)."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5,
                 rng: Optional[np.random.RandomState] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        h, s, v = _bgr_to_hsv(np.clip(f.image, 0, 255))
        s = np.clip(s * self.rng.uniform(self.low, self.high), 0, 1)
        f.image = _hsv_to_bgr(h, s, v)
        return f


class Hue(FeatureTransformer):
    """Rotate HSV hue by a delta in degrees
    (reference augmentation/Hue.scala)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 rng: Optional[np.random.RandomState] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        h, s, v = _bgr_to_hsv(np.clip(f.image, 0, 255))
        h = (h + self.rng.uniform(self.low, self.high) / 360.0) % 1.0
        f.image = _hsv_to_bgr(h, s, v)
        return f


class ChannelOrder(FeatureTransformer):
    """Reverse channel order BGR↔RGB
    (reference augmentation/ChannelOrder.scala)."""

    def transform(self, f):
        f.image = f.image[:, :, ::-1].copy()
        return f


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel
    (reference augmentation/ChannelNormalize.scala)."""

    def __init__(self, mean_b: float, mean_g: float, mean_r: float,
                 std_b: float = 1.0, std_g: float = 1.0,
                 std_r: float = 1.0):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.std = np.asarray([std_b, std_g, std_r], np.float32)

    def transform(self, f):
        f.image = (f.image - self.mean) / self.std
        return f


class ChannelScaledNormalizer(FeatureTransformer):
    """Per-channel mean subtraction + global scale
    (reference augmentation/ChannelScaledNormalizer.scala)."""

    def __init__(self, mean_b: float, mean_g: float, mean_r: float,
                 scale: float = 1.0):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.scale = scale

    def transform(self, f):
        f.image = (f.image - self.mean) * self.scale
        return f


class PixelNormalizer(FeatureTransformer):
    """Subtract a full per-pixel mean image
    (reference augmentation/PixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, f):
        f.image = f.image - self.means.reshape(f.image.shape)
        return f


class ColorJitter(FeatureTransformer):
    """Random brightness/contrast/saturation in random order
    (reference augmentation/ColorJitter.scala)."""

    def __init__(self, brightness: float = 32.0, contrast: float = 0.5,
                 saturation: float = 0.5, shuffle: bool = True,
                 rng: Optional[np.random.RandomState] = None):
        self.rng = rng or np.random.RandomState()
        self.stages = [
            Brightness(-brightness, brightness, rng=self.rng),
            Contrast(1 - contrast, 1 + contrast, rng=self.rng),
            Saturation(1 - saturation, 1 + saturation, rng=self.rng),
        ]
        self.shuffle = shuffle

    def transform(self, f):
        order = (self.rng.permutation(len(self.stages)) if self.shuffle
                 else range(len(self.stages)))
        for i in order:
            f = self.stages[i].transform(f)
        f.image = np.clip(f.image, 0, 255)
        return f


# --------------------------------------------------------------------------
# geometric transforms
# --------------------------------------------------------------------------

def _pil_resize(img: np.ndarray, w: int, h: int,
                method: str = "bilinear") -> np.ndarray:
    from PIL import Image as PILImage
    m = {"bilinear": PILImage.BILINEAR, "nearest": PILImage.NEAREST,
         "bicubic": PILImage.BICUBIC, "area": PILImage.BOX}[method]
    chans = [PILImage.fromarray(img[:, :, c]).resize((w, h), m)
             for c in range(img.shape[2])]
    return np.stack([np.asarray(c, np.float32) for c in chans], axis=-1)


class Resize(FeatureTransformer):
    """Resize to (resize_w, resize_h)
    (reference augmentation/Resize.scala)."""

    def __init__(self, resize_h: int, resize_w: int,
                 method: str = "bilinear"):
        self.h, self.w = resize_h, resize_w
        self.method = method

    def transform(self, f):
        f.image = _pil_resize(f.image, self.w, self.h, self.method)
        return f


class AspectScale(FeatureTransformer):
    """Resize so the short side is ``min_size`` with the long side capped
    at ``max_size`` (reference ScaleResize/AspectScale semantics used by
    detection pipelines).  ``max_size=None`` disables the cap — the
    short side is then always exactly ``min_size``, which crop-based
    classification pipelines rely on."""

    def __init__(self, min_size: int, max_size: Optional[int] = 1000,
                 scale_multiple: int = 1):
        self.min_size, self.max_size = min_size, max_size
        self.mult = scale_multiple

    def transform(self, f):
        h, w = f.image.shape[:2]
        scale = self.min_size / min(h, w)
        if self.max_size is not None and max(h, w) * scale > self.max_size:
            scale = self.max_size / max(h, w)
        nh, nw = int(round(h * scale)), int(round(w * scale))
        if self.mult > 1:
            nh = (nh // self.mult) * self.mult
            nw = (nw // self.mult) * self.mult
        f["scale"] = (nh / h, nw / w)
        f.image = _pil_resize(f.image, nw, nh)
        return f


class ScaleResize(AspectScale):
    """Alias of AspectScale (reference augmentation/ScaleResize.scala)."""


class RandomResize(FeatureTransformer):
    """Resize to a random size in [min, max] keeping square target
    (reference augmentation/RandomResize.scala)."""

    def __init__(self, min_size: int, max_size: int,
                 rng: Optional[np.random.RandomState] = None):
        self.min_size, self.max_size = min_size, max_size
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        s = int(self.rng.randint(self.min_size, self.max_size + 1))
        f.image = _pil_resize(f.image, s, s)
        return f


class CenterCrop(FeatureTransformer):
    """(reference augmentation/Crop.scala CenterCrop)."""

    def __init__(self, crop_w: int, crop_h: int):
        self.w, self.h = crop_w, crop_h

    def transform(self, f):
        H, W = f.image.shape[:2]
        y0 = max((H - self.h) // 2, 0)
        x0 = max((W - self.w) // 2, 0)
        f.image = f.image[y0:y0 + self.h, x0:x0 + self.w]
        return f


class RandomCrop(FeatureTransformer):
    """(reference augmentation/Crop.scala RandomCrop)."""

    def __init__(self, crop_w: int, crop_h: int,
                 rng: Optional[np.random.RandomState] = None):
        self.w, self.h = crop_w, crop_h
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        H, W = f.image.shape[:2]
        y0 = self.rng.randint(0, max(H - self.h, 0) + 1)
        x0 = self.rng.randint(0, max(W - self.w, 0) + 1)
        f.image = f.image[y0:y0 + self.h, x0:x0 + self.w]
        return f


class FixedCrop(FeatureTransformer):
    """Crop a fixed box, absolute pixels or normalized [0,1] coords
    (reference augmentation/Crop.scala FixedCrop)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = False):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def transform(self, f):
        H, W = f.image.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * W, x2 * W
            y1, y2 = y1 * H, y2 * H
        f.image = f.image[int(y1):int(y2), int(x1):int(x2)]
        return f


class RandomCropper(FeatureTransformer):
    """Random crop with HFlip for classification training
    (reference augmentation/RandomCropper.scala)."""

    def __init__(self, crop_w: int, crop_h: int, mirror: bool = True,
                 rng: Optional[np.random.RandomState] = None):
        self.rng = rng or np.random.RandomState()
        self.crop = RandomCrop(crop_w, crop_h, rng=self.rng)
        self.mirror = mirror

    def transform(self, f):
        f = self.crop.transform(f)
        if self.mirror and self.rng.rand() < 0.5:
            f.image = f.image[:, ::-1].copy()
        return f


class RandomAlterAspect(FeatureTransformer):
    """Random area+aspect-ratio crop then resize (GoogLeNet-style;
    reference augmentation/RandomAlterAspect.scala)."""

    def __init__(self, min_area_ratio: float = 0.08,
                 max_area_ratio: float = 1.0,
                 min_aspect_ratio_change: float = 0.75,
                 interp_mode: str = "bilinear", crop_length: int = 224,
                 rng: Optional[np.random.RandomState] = None):
        self.min_area, self.max_area = min_area_ratio, max_area_ratio
        self.min_ar = min_aspect_ratio_change
        self.method = interp_mode
        self.out = crop_length
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        H, W = f.image.shape[:2]
        area = H * W
        for _ in range(10):
            target = self.rng.uniform(self.min_area, self.max_area) * area
            ar = self.rng.uniform(self.min_ar, 1.0 / self.min_ar)
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if w <= W and h <= H:
                y0 = self.rng.randint(0, H - h + 1)
                x0 = self.rng.randint(0, W - w + 1)
                crop = f.image[y0:y0 + h, x0:x0 + w]
                f.image = _pil_resize(crop, self.out, self.out, self.method)
                return f
        f.image = _pil_resize(f.image, self.out, self.out, self.method)
        return f


class Expand(FeatureTransformer):
    """Place the image on a larger mean-filled canvas (SSD zoom-out;
    reference augmentation/Expand.scala)."""

    def __init__(self, means_b: float = 123.0, means_g: float = 117.0,
                 means_r: float = 104.0, min_expand_ratio: float = 1.0,
                 max_expand_ratio: float = 4.0,
                 rng: Optional[np.random.RandomState] = None):
        self.means = np.asarray([means_b, means_g, means_r], np.float32)
        self.min_ratio, self.max_ratio = min_expand_ratio, max_expand_ratio
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        H, W, C = f.image.shape
        ratio = self.rng.uniform(self.min_ratio, self.max_ratio)
        nh, nw = int(H * ratio), int(W * ratio)
        y0 = int(self.rng.uniform(0, nh - H))
        x0 = int(self.rng.uniform(0, nw - W))
        canvas = np.tile(self.means, (nh, nw, 1)).astype(np.float32)
        canvas[y0:y0 + H, x0:x0 + W] = f.image
        f["expand_offset"] = (y0, x0)
        f.image = canvas
        return f


class Filler(FeatureTransformer):
    """Fill a normalized sub-rectangle with a constant value
    (reference augmentation/Filler.scala)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def transform(self, f):
        H, W = f.image.shape[:2]
        x1, y1, x2, y2 = self.box
        f.image[int(y1 * H):int(y2 * H), int(x1 * W):int(x2 * W)] = \
            self.value
        return f


class HFlip(FeatureTransformer):
    """Unconditional horizontal flip (reference augmentation/HFlip.scala;
    use RandomTransformer(HFlip(), 0.5) for the random variant)."""

    def transform(self, f):
        f.image = f.image[:, ::-1].copy()
        f["flipped"] = True
        return f


class RandomTransformer(FeatureTransformer):
    """Apply inner transformer with probability p
    (reference augmentation/RandomTransformer.scala)."""

    def __init__(self, inner: FeatureTransformer, prob: float,
                 rng: Optional[np.random.RandomState] = None):
        self.inner = inner
        self.prob = prob
        self.rng = rng or np.random.RandomState()

    def transform(self, f):
        if self.rng.rand() < self.prob:
            f = self.inner.transform(f)
        return f


# --------------------------------------------------------------------------
# ROI label transforms (reference transform/vision/image/label/roi/*)
# --------------------------------------------------------------------------

class RoiNormalize(FeatureTransformer):
    """Normalize bbox coords to [0,1] by image size."""

    def transform(self, f):
        boxes = f.get(ImageFeature.bounding_box)
        if boxes is not None:
            H, W = f.image.shape[:2]
            boxes = np.asarray(boxes, np.float32)
            boxes[:, [0, 2]] /= W
            boxes[:, [1, 3]] /= H
            f[ImageFeature.bounding_box] = boxes
        return f


class RoiHFlip(FeatureTransformer):
    """Mirror bbox x coords; pair with HFlip on the pixels."""

    def __init__(self, normalized: bool = True):
        self.normalized = normalized

    def transform(self, f):
        boxes = f.get(ImageFeature.bounding_box)
        if boxes is not None:
            boxes = np.asarray(boxes, np.float32)
            w = 1.0 if self.normalized else f.image.shape[1]
            x1 = boxes[:, 0].copy()
            boxes[:, 0] = w - boxes[:, 2]
            boxes[:, 2] = w - x1
            f[ImageFeature.bounding_box] = boxes
        return f


class RoiResize(FeatureTransformer):
    """Scale absolute bbox coords by the recorded resize scale."""

    def transform(self, f):
        boxes = f.get(ImageFeature.bounding_box)
        scale = f.get("scale")
        if boxes is not None and scale is not None:
            boxes = np.asarray(boxes, np.float32)
            sy, sx = scale
            boxes[:, [0, 2]] *= sx
            boxes[:, [1, 3]] *= sy
            f[ImageFeature.bounding_box] = boxes
        return f


# --------------------------------------------------------------------------
# bridge to the training pipeline
# --------------------------------------------------------------------------

class MatToTensor(FeatureTransformer):
    """Finalize the float image (÷ optional scale, HWC float32) —
    reference MatToTensor/MatToFloats."""

    def __init__(self, scale: float = 1.0):
        self.scale = scale

    def transform(self, f):
        f.image = np.ascontiguousarray(f.image, np.float32) * self.scale
        return f


class ImageFrameToSample(Transformer):
    """ImageFeature iterator → Sample iterator
    (reference ImageFrameToSample.scala)."""

    def apply(self, it):
        for f in it:
            yield Sample(f.image, f.get_label())
