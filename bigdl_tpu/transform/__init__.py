from bigdl_tpu.transform.vision import *  # noqa: F401,F403
