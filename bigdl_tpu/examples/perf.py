"""Training-throughput perf harness CLI (reference
models/utils/DistriOptimizerPerf.scala — the distributed iters/sec
benchmark main — plus nn/mkldnn/Perf.scala's local latency mode).

    bigdl-tpu-perf --model resnet50 -b 128 --bf16
    bigdl-tpu-perf --model transformer-lm --seq-len 512 -b 16
    bigdl-tpu-perf --model lenet -b 256 --iterations 50

Drives the REAL ``Optimizer.optimize()`` loop (mesh, donation, async
readback) on synthetic device-cached data and prints one JSON line:
records/sec and ms/iteration from the Optimizer's completion-to-
completion window telemetry (the first window bears trace+compile and
is excluded — same methodology as bench.py).
"""

from __future__ import annotations

import argparse
import json
import time


MODELS = ("lenet", "resnet50", "inception-v1", "inception-v2", "vgg16",
          "transformer-lm", "ptb-lstm")


def build(name: str, args):
    """→ (model, criterion, make_batch(batch_size) → (x, y))"""
    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu import models

    rng = np.random.default_rng(0)
    size = args.image_size

    def image_batch(b):
        return (rng.normal(size=(b, size, size, 3)).astype(np.float32),
                rng.integers(1, args.classes + 1, size=(b,)))

    if name == "lenet":
        def mnist_batch(b):
            return (rng.normal(size=(b, 28, 28, 1)).astype(np.float32),
                    rng.integers(1, 11, size=(b,)))
        return models.LeNet5(10), nn.ClassNLLCriterion(), mnist_batch
    if name == "resnet50":
        return (models.resnet50(args.classes,
                                fused=getattr(args, "fused", False)),
                nn.CrossEntropyCriterion(), image_batch)
    if name == "inception-v1":
        # both inception towers end in log_softmax: ClassNLL consumes
        # the log-probs directly
        return (models.Inception_v1(args.classes),
                nn.ClassNLLCriterion(), image_batch)
    if name == "inception-v2":
        return (models.Inception_v2(args.classes),
                nn.ClassNLLCriterion(), image_batch)
    if name == "vgg16":
        return (models.Vgg_16(args.classes),
                nn.CrossEntropyCriterion(), image_batch)
    def token_batch(b):
        return (rng.integers(
                    1, args.vocab_size + 1,
                    size=(b, args.seq_len)).astype(np.int32),
                rng.integers(1, args.vocab_size + 1,
                             size=(b * args.seq_len,)).astype(np.int32))

    if name == "transformer-lm":
        # synthetic batches are contiguous (tokens 1..V, no padding):
        # padded_inputs=False keeps the causal mask inside the kernel
        # (flash skips above-diagonal blocks, no [B,H,T,T] bias)
        lm = models.transformer_lm(
            vocab_size=args.vocab_size, hidden_size=args.hidden_size,
            num_layers=args.num_layers, num_heads=args.num_heads,
            filter_size=4 * args.hidden_size, max_len=args.seq_len,
            remat=args.remat, padded_inputs=False)
        return _flat_lm(lm), nn.CrossEntropyCriterion(), token_batch
    if name == "ptb-lstm":
        # The reference's PTB word LM (example/languagemodel/
        # PTBModel.scala): embedding -> stacked LSTM (lax.scan over
        # time) -> TimeDistributed decoder -> logsoftmax, trained with
        # ClassNLL on flattened [B*T] targets.
        from bigdl_tpu.models.rnn_lm import PTBModel

        lm = PTBModel(args.vocab_size, hidden_size=args.hidden_size,
                      num_layers=args.num_layers)
        return _flat_lm(lm), nn.ClassNLLCriterion(), token_batch
    raise SystemExit(f"unknown --model {name!r}")


def _flat_lm(lm):
    """Wrap a [B,T,V]-output LM to emit [B*T, V] for the flat-target
    criteria (both LM perf models share this).  A factory (not a
    module-level class) so bigdl_tpu imports stay lazy for CLI startup."""
    from bigdl_tpu.core.module import Module

    class Flat(Module):
        def __init__(self):
            super().__init__()
            self.lm = lm

        def forward(self, x):
            out = self.lm.forward(x)
            return out.reshape(-1, out.shape[-1])

    return Flat()


def write_jpeg_tree(n: int, size: int = 256) -> str:
    """Write n real JPEG files into a temp class-per-subdirectory tree
    (2 classes).  Real libjpeg decode work without the dataset."""
    import os as _os
    import tempfile

    import numpy as np
    from PIL import Image

    folder = tempfile.mkdtemp(prefix="bigdl_tpu_ipbench_")
    rng = np.random.default_rng(0)
    for c in range(2):
        cdir = f"{folder}/class{c}"
        _os.makedirs(cdir, exist_ok=True)
        for i in range(n // 2):
            arr = rng.integers(0, 256, size=(size, size, 3),
                               dtype=np.uint8)
            Image.fromarray(arr).save(f"{cdir}/{i}.jpg", quality=85)
    return folder


def bench_input_pipeline(folder, image_size, batch_size, workers,
                         synthetic_n=0):
    """Host input-pipeline throughput: jpeg decode + train augmentation
    + batching, NO device work (the number that must exceed the device
    step rate for the TPU to stay fed; VERDICT r03 flagged that no such
    number existed).  ``synthetic_n`` > 0 writes that many JPEGs to a
    temp class-folder tree first — evidence for the real jpeg path
    without the dataset."""
    import itertools
    import shutil
    import numpy as np

    tmp = None
    if synthetic_n:
        tmp = folder = write_jpeg_tree(synthetic_n)
    elif folder is None:
        raise ValueError(
            "bench_input_pipeline needs a folder or synthetic_n > 0")

    try:
        from bigdl_tpu.examples.imagenet import train_pipeline
        data, classes, _ = train_pipeline(folder, image_size, batch_size,
                                          workers=workers)
        # bounded warmup (OS page cache + jpeg codec init); a full warm
        # epoch would decode a real ImageNet train split twice
        for batch in itertools.islice(data.data(train=True), 2):
            batch.get_input()
        t0 = time.perf_counter()
        n = 0
        for batch in data.data(train=True):
            n += batch.get_input().shape[0]
        dt = time.perf_counter() - t0
        return {
            "input_pipeline_img_per_sec": round(n / dt, 1),
            "images": n, "workers": workers, "image_size": image_size,
        }
    finally:
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def bench_generate(args):
    """KV-cache greedy-decode throughput for the transformer LM: a
    --seq-len prompt prefills the caches, then --generate N tokens
    decode one at a time (reference: the Transformer.scala +
    SequenceBeamSearch inference path; here the incremental
    decode_step the reference lacks).

    Decode time is isolated by DIFFERENCING: generating N and 2N new
    tokens from the same prompt shares the identical prefill, so
    (t_2N - t_N)/N is pure per-token decode cost — a single gen(N)
    timing would charge the whole prompt forward to the decode tokens.
    Timing forces completion with a device readback of the token ids."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from bigdl_tpu import models
    from bigdl_tpu.utils import set_seed

    if args.model != "transformer-lm":
        raise SystemExit("--generate requires --model transformer-lm")
    new = args.generate
    set_seed(0)
    lm = models.transformer_lm(
        vocab_size=args.vocab_size, hidden_size=args.hidden_size,
        num_layers=args.num_layers, num_heads=args.num_heads,
        filter_size=4 * args.hidden_size,
        max_len=args.seq_len + 2 * new).eval_mode()
    if args.bf16:
        from bigdl_tpu.core.module import cast_floating
        lm = cast_floating(lm, jnp.bfloat16)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(
        1, args.vocab_size + 1,
        size=(args.batch_size, args.seq_len)).astype(np.int32))

    reps = 3
    compile_s = 0.0
    times = {}
    for n_new in (new, 2 * new):
        gen = jax.jit(lambda p, n=n_new: lm.generate(p, n))
        t0 = time.perf_counter()
        np.asarray(gen(prompt))  # forced completion
        compile_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(reps):
            out = gen(prompt)
        np.asarray(out)
        times[n_new] = (time.perf_counter() - t0) / reps
    decode_s = max(times[2 * new] - times[new], 1e-9) / new
    prefill_s = max(times[new] - new * decode_s, 0.0)
    return {
        "model": "transformer-lm",
        "mode": "generate",
        "batch_size": args.batch_size,
        "prompt_len": args.seq_len,
        "new_tokens": new,
        "decode_tokens_per_sec": round(args.batch_size / decode_s, 1),
        "ms_per_decoded_token": round(decode_s * 1e3, 3),
        "prefill_ms": round(prefill_s * 1e3, 3),
        "e2e_tokens_per_sec": round(
            args.batch_size * new / times[new], 1),
        "compile_plus_first_run_s": round(compile_s, 2),
        "bf16": bool(args.bf16),
    }


def bench_int8_inference(args):
    """fp32-vs-int8 inference latency on the same trained-shape model
    (reference: whitepaper.md:192-196 claims up to 2x on BigQuant CPU
    GEMM; here both paths are XLA on the accelerator — int8 rides the
    MXU's int8 throughput via dot_general/conv preferred_element_type).
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.nn.quantized import Quantizer
    from bigdl_tpu.utils import set_seed

    set_seed(0)
    model, _, make_batch = build(args.model, args)
    model.eval_mode()
    x_np, _ = make_batch(args.batch_size)
    x = jnp.asarray(x_np)
    qmodel = Quantizer.quantize(model)  # clones internally
    if args.bf16:
        # compare against the bf16 production baseline, mirroring the
        # training/--generate modes; int8 path keeps its own dtypes.
        # cast_floating on the input leaves integer batches (token
        # ids) alone
        from bigdl_tpu.core.module import cast_floating
        model = cast_floating(model, jnp.bfloat16)
        x = cast_floating(x, jnp.bfloat16)

    def timed(m):
        fwd = jax.jit(lambda inp: m.forward(inp))
        out = fwd(x)
        np.asarray(out)  # forced completion
        reps = 10
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fwd(x)
        np.asarray(out)
        return (time.perf_counter() - t0) / reps

    t_base = timed(model)
    t_int8 = timed(qmodel)
    base = "bf16" if args.bf16 else "fp32"
    return {
        "model": args.model,
        "mode": "int8-infer",
        "batch_size": args.batch_size,
        "baseline_dtype": base,
        f"{base}_ms": round(t_base * 1e3, 3),
        "int8_ms": round(t_int8 * 1e3, 3),
        "int8_speedup": round(t_base / t_int8, 3),
        f"{base}_img_per_sec": round(args.batch_size / t_base, 1),
        "int8_img_per_sec": round(args.batch_size / t_int8, 1),
    }


def main(argv=None, emit=True):
    p = argparse.ArgumentParser(
        description="Benchmark the Optimizer training loop on a model")
    p.add_argument("--model", default="resnet50", choices=MODELS)
    p.add_argument("--input-pipeline", metavar="FOLDER", default=None,
                   help="measure the HOST jpeg->batch pipeline only "
                        "(pass 'synthetic' to generate test JPEGs)")
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--synthetic-images", type=int, default=512)
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--iterations", type=int, default=20,
                   help="iterations per timed epoch")
    p.add_argument("--epochs", type=int, default=4,
                   help="total epochs (first pays compile)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--vocab-size", type=int, default=1000)
    p.add_argument("--hidden-size", type=int, default=256)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--real-jpeg-train", type=int, default=0, metavar="N",
                   help="train from N REAL jpeg files through the "
                        "production imagenet input pipeline instead of "
                        "device-cached synthetic batches; reports the "
                        "end-to-end step rate next to the host-only "
                        "pipeline rate")
    p.add_argument("--fused", action="store_true",
                   help="resnet50: fused conv+BN+ReLU Pallas bottleneck "
                        "path (TPU; falls back to plain off-TPU)")
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--learning-rate", type=float, default=0.01)
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="transformer-lm only: measure KV-cache greedy "
                        "decode of N new tokens after a --seq-len "
                        "prompt instead of training")
    p.add_argument("--int8-infer", action="store_true",
                   help="measure fp32-vs-int8 inference latency on the "
                        "quantized model instead of training")
    args = p.parse_args(argv)

    # multi-host bootstrap (no-op off-pod) before any backend use
    from bigdl_tpu.utils import Engine
    Engine.init_distributed()

    if args.input_pipeline:
        if args.input_pipeline == "synthetic":
            if args.synthetic_images <= 0:
                raise SystemExit(
                    "--input-pipeline synthetic needs "
                    "--synthetic-images > 0")
            synth, folder = args.synthetic_images, None
        else:
            synth, folder = 0, args.input_pipeline
        out = bench_input_pipeline(
            folder, args.image_size, args.batch_size, args.workers,
            synthetic_n=synth)
        if emit:
            print(json.dumps(out), flush=True)
        return out

    if args.generate:
        out = bench_generate(args)
        if emit:
            print(json.dumps(out), flush=True)
        return out

    if args.int8_infer:
        out = bench_int8_inference(args)
        if emit:
            print(json.dumps(out), flush=True)
        return out

    from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils import set_seed

    set_seed(0)
    real_tmp = None
    if args.real_jpeg_train:
        # REAL-data feed: JPEG files through the production imagenet
        # train pipeline (decode + augment on the host, args.workers
        # threads) into the live Optimizer loop — the step rate is
        # host-bound whenever the pipeline cannot keep the device fed,
        # so records_per_sec here IS the end-to-end claim (VERDICT r04
        # missing #4; ≙ models/resnet/TrainImageNet.scala's SeqFile
        # path feeding DistriOptimizer)
        from bigdl_tpu.examples.imagenet import train_pipeline
        real_tmp = write_jpeg_tree(args.real_jpeg_train)
        # exceptions anywhere below (or a harness deadline) must not
        # leak the multi-MB tree: tie cleanup to interpreter exit (a
        # SIGKILL leaks regardless; a finally would too)
        import atexit
        import shutil
        atexit.register(shutil.rmtree, real_tmp, ignore_errors=True)
        data, n_classes, _ = train_pipeline(
            real_tmp, args.image_size, args.batch_size,
            workers=args.workers)
        args.classes = n_classes
        args.iterations = max(args.real_jpeg_train
                              // args.batch_size, 1)
        model, criterion, _ = build(args.model, args)
        host_only = bench_input_pipeline(
            real_tmp, args.image_size, args.batch_size, args.workers)
    else:
        model, criterion, make_batch = build(args.model, args)
        x, y = make_batch(args.batch_size)
        # one shared host buffer per epoch-slot: the device cache holds
        # it once (≙ CachedDistriDataSet)
        data = DataSet.array(
            [MiniBatch(x, y) for _ in range(args.iterations)],
            shuffle=False).cache_on_device()
    opt = (Optimizer(model, data, criterion)
           .set_optim_method(SGD(args.learning_rate, momentum=0.9,
                                 dampening=0.0))
           .set_end_when(Trigger.max_epoch(args.epochs))
           .set_log_interval(args.iterations))
    if args.bf16:
        import jax.numpy as jnp
        opt.set_compute_dtype(jnp.bfloat16)
    t0 = time.perf_counter()
    opt.optimize()
    total = time.perf_counter() - t0

    # Steady-state step time from the Optimizer's completion-to-
    # completion window telemetry (each window's timestamp is pinned by
    # a blocking transfer of its last loss, so it cannot fire before
    # the device really finished).  Epoch-start wall gaps would measure
    # DISPATCH rate — under the async loss drain the loop dispatches
    # epochs far faster than the device retires them, so that number
    # can be off by >20x (the r02 bench lie).  The AGGREGATE span over
    # all steady windows is the robust estimator: when the drain lags
    # a window, later completions bunch together and a min() over
    # per-window rates reads impossibly fast, but the first steady
    # window is observed promptly (the drain idles waiting on it) and
    # the last can only be observed late, so the span is device-honest.
    steady = opt.window_timings[1:]  # window 1 bears trace+compile
    if steady:
        step_s = sum(dt for _, dt, _ in steady) / sum(
            n for n, _, _ in steady)
    else:  # single window: wall time includes compile; flagged below
        step_s = total / args.iterations
    out = {
        "model": args.model,
        "batch_size": args.batch_size,
        "records_per_sec": round(args.batch_size / step_s, 2),
        "ms_per_iteration": round(step_s * 1e3, 3),
        **({"mode": "real-jpeg-train",
            "real_images": args.real_jpeg_train,
            "workers": args.workers,
            "host_pipeline_img_per_sec":
                host_only["input_pipeline_img_per_sec"]}
           if real_tmp else {}),
        "windows_timed": len(steady),
        "compile_plus_first_window_s": round(
            opt.window_timings[0][1] if opt.window_timings else total, 2),
        "bf16": bool(args.bf16),
    }
    if opt.compiled_flops_per_iteration:
        # XLA's own FLOP count of the executed program (fwd+bwd+update),
        # already normalized per train iteration by the Optimizer
        flops_step = opt.compiled_flops_per_iteration
        out["flops_per_iteration"] = flops_step
        out["model_tflops_per_sec"] = round(flops_step / step_s / 1e12, 3)
    if not steady:
        out["warning"] = ("single dispatch window: time includes "
                          "compile; run more iterations/epochs for "
                          "steady-state numbers")
    if emit:
        print(json.dumps(out), flush=True)
    return out


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(dict) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
