"""Runnable end-user entry points (reference models/*/Train.scala CLI
mains + example/ suite).  Installed as console scripts:

    bigdl-tpu-lenet         LeNet-5 on MNIST
    bigdl-tpu-resnet-cifar  ResNet-20/32/... on CIFAR-10
    bigdl-tpu-ptb           PTB word-level LSTM LM

Each mirrors its reference scopt CLI (folder/batch/epochs/lr/checkpoint/
summaries) and falls back to synthetic data with ``--synthetic`` so the
end-to-end path runs in zero-egress environments.
"""
