"""MNIST autoencoder training main.

Reference: models/autoencoder/Train.scala (GreyImgToBatch →
``toAutoencoderBatch`` makes the TARGET the input image itself;
MSECriterion; Adagrad lr 0.01, weight decay 5e-4, Trigger.maxEpoch).

    bigdl-tpu-autoencoder -f /data/mnist -b 150 -e 10
    bigdl-tpu-autoencoder --synthetic 1024 -e 3
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.examples.common import apply_common, base_parser, setup


def to_reconstruction_samples(samples):
    """toAutoencoderBatch semantics: label := the image, flattened and
    min-max squashed to [0,1] so the sigmoid decoder can reach it."""
    from bigdl_tpu.dataset import Sample

    out = []
    for s in samples:
        f = np.asarray(s.feature, np.float32)
        flat = f.reshape(-1)
        lo, hi = float(flat.min()), float(flat.max())
        target = (flat - lo) / max(hi - lo, 1e-6)
        out.append(Sample(f, target))
    return out


def synthetic_split(n: int, batch_size: int):
    """Synthetic train/validation split: draw n + n_val samples in ONE
    generation (synthetic_mnist prototypes depend on both seed and
    count, so train and val must come from the same draw) keeping the
    full requested n — and at least one batch — for training."""
    from bigdl_tpu.dataset.mnist import synthetic_mnist

    n_val = max(n // 10, batch_size)
    samples = synthetic_mnist(n + n_val, seed=0)
    return samples[:n], samples[n:]


def main(argv=None):
    p = base_parser("Train the MNIST autoencoder")
    p.add_argument("--bottleneck", type=int, default=32,
                   help="encoder output width (reference classNum)")
    # the reference recipe's Adagrad lr (models/autoencoder/Train.scala)
    p.set_defaults(learning_rate=0.01)
    args = p.parse_args(argv)
    train_summary, val_summary = setup(args, "autoencoder")

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.mnist import mnist_samples
    from bigdl_tpu.models import Autoencoder
    from bigdl_tpu.optim import Loss, Optimizer, Trigger
    from bigdl_tpu.optim.methods import Adagrad

    if args.synthetic:
        train_s, test_s = synthetic_split(args.synthetic, args.batch_size)
    else:
        train_s = mnist_samples(args.folder, train=True)
        test_s = mnist_samples(args.folder, train=False)
    train = to_reconstruction_samples(train_s)
    test = to_reconstruction_samples(test_s)

    # clamp so a small smoke run still yields at least one full batch
    # (SampleToMiniBatch drops ragged tails)
    batch = min(args.batch_size, len(train))
    data = DataSet.array(train).transform(SampleToMiniBatch(batch))
    if args.cache_device:
        data = data.cache_on_device()
    model = Autoencoder(class_num=args.bottleneck)
    opt = (Optimizer(model, data, nn.MSECriterion())
           .set_optim_method(Adagrad(learning_rate=args.learning_rate,
                                     weight_decay=5e-4))
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_validation(Trigger.every_epoch(), test,
                           [Loss(nn.MSECriterion())],
                           batch_size=min(batch, len(test))))
    apply_common(opt, args, train_summary, val_summary)
    opt.optimize()
    print(f"Final reconstruction loss: {opt.state['loss']:.5f}")
    return model


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
