"""ImageNet-style training main for the large vision models
(reference models/resnet/TrainImageNet.scala + models/inception/
Train.scala; README recipe at models/resnet/README.md:85-150).

    bigdl-tpu-imagenet -f /data/imagenet --model resnet50 -b 256 --bf16
    bigdl-tpu-imagenet --synthetic 512 --model inception-v1 -e 1

Data layout: ``<folder>/train/<class>/*.jpg`` and
``<folder>/val/<class>/*.jpg`` (class-per-subdirectory).  The input
pipeline is the reference's: aspect-preserving short-side-256 scale →
random-crop-224 + HFlip + channel-normalize for training,
center-crop-224 for validation — all host-side so the jitted step gets
ready NHWC arrays.
"""

from __future__ import annotations

import os

from bigdl_tpu.examples.common import apply_common, base_parser, setup

# ImageNet RGB mean/std on the [0, 255] scale (reference
# models/resnet/ImageNet dataset constants)
MEAN = (123.68, 116.779, 103.939)
STD = (58.395, 57.12, 57.375)


MODELS = {"resnet50": "resnet50",
          "inception-v1": "Inception_v1",
          "inception-v2": "Inception_v2",
          "vgg16": "Vgg_16"}


def _build_model(name: str, class_num: int):
    from bigdl_tpu import models
    return getattr(models, MODELS[name])(class_num)


def _short_side(size: int) -> int:
    """Short-side resize target for a given crop size (256 for 224
    crops, scaled proportionally) — shared by the augment recipe AND
    the native decoder's minimum decode size so they cannot drift."""
    return max(size * 256 // 224, size)


class _Augment:
    """Sample-level wrapper over the vision FeatureTransformers:
    aspect-preserving short-side scale (256 for 224-px crops, scaled
    with the crop size) followed by random/center crop."""

    def __init__(self, train: bool, size: int = 224):
        from bigdl_tpu.transform.vision import (
            AspectScale, CenterCrop, ChannelNormalize, HFlip, RandomCrop,
            RandomTransformer,
        )
        # short-side resize preserving aspect ratio, then crop — the
        # standard recipe (reference RandomAlterAspect/RandomCropper for
        # train, Resize(short=256)+CenterCrop(224) for eval); a square
        # Resize(r, r) would distort non-square images.  The long side
        # is uncapped: a max_size cap could shrink the short side below
        # the crop and crash batching on extreme panoramas.
        r = _short_side(size)
        scale = AspectScale(r, max_size=None)
        if train:
            self.stages = [scale, RandomCrop(size, size),
                           RandomTransformer(HFlip(), 0.5),
                           ChannelNormalize(*MEAN, *STD)]
        else:
            self.stages = [scale, CenterCrop(size, size),
                           ChannelNormalize(*MEAN, *STD)]

    def apply_one(self, image):
        """HWC array → augmented HWC array (single copy of the stage
        loop, shared by the sequential and ParallelMap paths)."""
        from bigdl_tpu.transform.vision import ImageFeature
        feat = ImageFeature(image)
        for t in self.stages:
            feat = t(feat)
        return feat.image

    def __call__(self, it):
        from bigdl_tpu.dataset.dataset import Sample
        for s in it:
            yield Sample(self.apply_one(s.feature), s.label)


IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp", ".ppm")


def _list_image_folder(path: str, class_to_label=None):
    """Lazy ImageNet listing: (file path, 1-based label) pairs — images
    decode inside the pipeline, never all-at-once in host RAM.  Only
    image-extension files are listed (a stray README/.DS_Store must not
    abort a run mid-epoch).  Pass the training split's ``class_to_label``
    for the val split so labels share one mapping even when a class is
    missing from val."""
    classes = sorted(d for d in os.listdir(path)
                     if os.path.isdir(os.path.join(path, d)))
    if class_to_label is None:
        class_to_label = {cls: ci + 1 for ci, cls in enumerate(classes)}
    items = []
    for cls in classes:
        if cls not in class_to_label:
            raise SystemExit(
                f"class directory {cls!r} in {path} has no corresponding "
                f"training class (train classes: {sorted(class_to_label)})")
        cdir = os.path.join(path, cls)
        items.extend((os.path.join(cdir, fn), class_to_label[cls])
                     for fn in sorted(os.listdir(cdir))
                     if fn.lower().endswith(IMAGE_EXTS))
    return items, len(class_to_label), class_to_label


def _decode_rgb(path, min_short: int = 0):
    """path → HWC float32 RGB array (single decode expression shared by
    every pipeline so color handling cannot diverge).

    JPEGs go through the native libjpeg decoder when it built
    (bigdl_tpu.native.jpeg_decode_scaled): with ``min_short`` > 0 it
    DCT-downscales during decode so a 4000px photo headed for a 256px
    short side never materializes at full resolution — the AspectScale
    stage downstream then only closes the last <=2x gap.  Everything
    else (PNG/BMP/..., no native lib, corrupt data) falls back to PIL."""
    import numpy as np
    if path.lower().endswith((".jpg", ".jpeg")):
        from bigdl_tpu.native import jpeg_available, jpeg_decode_scaled
        arr = None
        if jpeg_available():   # cached; don't double-read on PIL hosts
            try:
                with open(path, "rb") as f:
                    data = f.read()
                arr = jpeg_decode_scaled(data, min_short)
            except OSError:
                arr = None
        if arr is not None:
            return arr.astype(np.float32)
    from PIL import Image
    return np.asarray(Image.open(path).convert("RGB"), np.float32)


class _DecodeAugment:
    """Per-item decode + augment for ParallelMap: PIL decode and numpy
    resampling release the GIL, so worker threads genuinely overlap
    (≙ the reference's MTImageFeatureToBatch per-thread pipelines).

    Each worker thread gets its OWN _Augment: RandomCrop and
    RandomTransformer hold legacy np.random.RandomState instances,
    which are not thread-safe — sharing one across workers could
    corrupt the Mersenne state or correlate the augmentation streams.
    Fresh RandomState() instances seed from OS entropy, so per-thread
    streams are independent."""

    def __init__(self, train: bool, size: int):
        import threading
        self._train, self._size = train, size
        # the augment's short-side target: decode no smaller than this
        self._min_short = _short_side(size)
        self._local = threading.local()

    def _aug(self) -> _Augment:
        aug = getattr(self._local, "aug", None)
        if aug is None:
            aug = self._local.aug = _Augment(train=self._train,
                                             size=self._size)
        return aug

    def __call__(self, item):
        from bigdl_tpu.dataset.dataset import Sample
        path, label = item
        return Sample(
            self._aug().apply_one(_decode_rgb(path, self._min_short)),
            label)


def train_pipeline(folder: str, size: int, batch_size: int,
                   workers: int = 8):
    """Class-per-subdirectory folder → (DataSet, n_classes, class_map)
    through the threaded TRAIN augment path (random crop/flip) +
    double-buffered prefetch — the pipeline the training main builds."""
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.prefetch import ParallelMap, Prefetch
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch
    items, classes, cmap = _list_image_folder(folder)
    data = (DataSet.array(items)
            .transform(ParallelMap(_DecodeAugment(train=True, size=size),
                                   workers=workers))
            .transform(SampleToMiniBatch(batch_size))
            .transform(Prefetch(2)))
    return data, classes, cmap


def eval_pipeline(folder: str, size: int, batch_size: int,
                  workers: int = 8, class_map=None):
    """Class-per-subdirectory folder → (DataSet, n_classes, class_map)
    through the threaded eval augment path — the one evaluation pipeline
    shared by the imagenet, loadmodel, and quantize CLIs."""
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.prefetch import ParallelMap
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch
    items, classes, cmap = _list_image_folder(folder, class_map)
    data = (DataSet.array(items, shuffle=False)
            .transform(ParallelMap(_DecodeAugment(train=False, size=size),
                                   workers=workers))
            .transform(SampleToMiniBatch(batch_size)))
    return data, classes, cmap


def _synthetic(n: int, size: int, classes: int, seed: int):
    """Per-class prototypes generated lazily from the label's own seed,
    so the full --classes head is honored without a classes-sized
    prototype tensor in RAM."""
    import numpy as np
    from bigdl_tpu.dataset.dataset import Sample
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, size=n)
    out = []
    for l in labels:
        proto = np.random.default_rng(10_000 + int(l)).normal(
            size=(size, size, 3))
        out.append(Sample((proto + 0.25 * rng.normal(
            size=(size, size, 3))).astype(np.float32), int(l) + 1))
    return out, classes


def main(argv=None):
    p = base_parser("Train ResNet-50 / Inception-v1 / VGG16 on ImageNet")
    p.add_argument("--model", default="resnet50", choices=sorted(MODELS))
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--warmup-epochs", type=int, default=0)
    p.add_argument("--workers", type=int, default=8,
                   help="decode/augment threads (folder input)")
    p.set_defaults(batch_size=256, learning_rate=0.1, max_epoch=90)
    args = p.parse_args(argv)
    train_summary, val_summary = setup(args, f"imagenet-{args.model}")

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim import (
        Loss, Optimizer, Poly, SGD, SequentialSchedule, Top1Accuracy,
        Top5Accuracy, Trigger, Warmup,
    )

    size = args.image_size
    val_data = None
    if args.synthetic:
        classes = args.classes
        train, _ = _synthetic(args.synthetic, size, classes, seed=0)
        val, _ = _synthetic(max(args.synthetic // 8, args.batch_size),
                            size, classes, seed=1)
        n_train = len(train)
        train_data = (DataSet.array(train)
                      .transform(SampleToMiniBatch(args.batch_size)))
        if args.cache_device:
            train_data = train_data.cache_on_device()
        val_data = (DataSet.array(val, shuffle=False)
                    .transform(SampleToMiniBatch(args.batch_size)))
    else:
        if args.cache_device:
            raise SystemExit(
                "--cache-device would freeze the random crops/flips of "
                "epoch 1 and replay them forever; it is only valid with "
                "--synthetic data")
        from bigdl_tpu.dataset.prefetch import Prefetch
        train_data, classes, class_map = train_pipeline(
            os.path.join(args.folder, "train"), size, args.batch_size,
            workers=args.workers)
        n_train = train_data.size()
        val_dir = os.path.join(args.folder, "val")
        if os.path.isdir(val_dir):
            val_data, _, _ = eval_pipeline(
                val_dir, size, args.batch_size, workers=args.workers,
                class_map=class_map)
            val_data = val_data.transform(Prefetch(2))

    model = _build_model(args.model, classes)
    iters_per_epoch = max(n_train // args.batch_size, 1)
    total_iters = args.max_epoch * iters_per_epoch
    base_lr = args.learning_rate
    if args.warmup_epochs > 0:
        # Linear ramp from a small starting lr up to the requested
        # --learning-rate (the peak), then Poly decay from the peak over
        # the remaining budget — the reference's large-batch recipe
        # (models/resnet/TrainImageNet.scala warmup: delta =
        # (maxLr - lr) / warmupIters inside SGD.SequentialSchedule).
        # SequentialSchedule hands each stage's final lr to the next
        # stage, so Poly decays exactly from the peak.
        warm_iters = args.warmup_epochs * iters_per_epoch
        if warm_iters >= total_iters:
            p.error(f"--warmup-epochs ({args.warmup_epochs}) must be "
                    f"smaller than --max-epoch ({args.max_epoch})")
        start_lr = args.learning_rate / warm_iters
        base_lr = start_lr
        schedule = (SequentialSchedule(iters_per_epoch)
                    .add(Warmup((args.learning_rate - start_lr)
                                / warm_iters), warm_iters)
                    .add(Poly(0.5, total_iters - warm_iters),
                         total_iters - warm_iters))
    else:
        schedule = Poly(0.5, total_iters)
    method = SGD(base_lr, momentum=args.momentum,
                 dampening=0.0, weight_decay=args.weight_decay,
                 nesterov=True, learning_rate_schedule=schedule)
    opt = (Optimizer(model, train_data, nn.CrossEntropyCriterion())
           .set_optim_method(method)
           .set_end_when(Trigger.max_epoch(args.max_epoch)))
    if val_data is not None:
        methods = [Top1Accuracy(), Loss(nn.CrossEntropyCriterion())]
        if classes >= 5:
            methods.insert(1, Top5Accuracy())
        opt.set_validation(Trigger.every_epoch(), val_data, methods)
    apply_common(opt, args, train_summary, val_summary)
    opt.optimize()
    if val_data is not None:
        print(f"Final validation score: {opt.state['score']:.4f}")
    return model


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
