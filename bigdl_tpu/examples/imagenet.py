"""ImageNet-style training main for the large vision models
(reference models/resnet/TrainImageNet.scala + models/inception/
Train.scala; README recipe at models/resnet/README.md:85-150).

    bigdl-tpu-imagenet -f /data/imagenet --model resnet50 -b 256 --bf16
    bigdl-tpu-imagenet --synthetic 512 --model inception-v1 -e 1

Data layout: ``<folder>/train/<class>/*.jpg`` and
``<folder>/val/<class>/*.jpg`` (class-per-subdirectory).  The input
pipeline is the reference's: resize-256 → random-crop-224 + HFlip +
channel-normalize for training, center-crop for validation — all
host-side so the jitted step gets ready NHWC arrays.
"""

from __future__ import annotations

import os

from bigdl_tpu.examples.common import apply_common, base_parser, setup

# ImageNet RGB mean/std on the [0, 255] scale (reference
# models/resnet/ImageNet dataset constants)
MEAN = (123.68, 116.779, 103.939)
STD = (58.395, 57.12, 57.375)


def _build_model(name: str, class_num: int):
    from bigdl_tpu import models
    table = {"resnet50": lambda: models.resnet50(class_num),
             "inception-v1": lambda: models.Inception_v1(class_num),
             "vgg16": lambda: models.Vgg_16(class_num)}
    if name not in table:
        raise SystemExit(f"unknown --model {name!r} "
                         f"(choose from {sorted(table)})")
    return table[name]()


class _Augment:
    """Sample-level wrapper over the vision FeatureTransformers.
    Resize scales with the crop size (256 is the reference value for
    224-px crops)."""

    def __init__(self, train: bool, size: int = 224):
        from bigdl_tpu.transform.vision import (
            CenterCrop, ChannelNormalize, HFlip, RandomCrop,
            RandomTransformer, Resize,
        )
        r = max(size * 256 // 224, size)
        if train:
            self.stages = [Resize(r, r), RandomCrop(size, size),
                           RandomTransformer(HFlip(), 0.5),
                           ChannelNormalize(*MEAN, *STD)]
        else:
            self.stages = [Resize(r, r), CenterCrop(size, size),
                           ChannelNormalize(*MEAN, *STD)]

    def __call__(self, it):
        from bigdl_tpu.dataset.dataset import Sample
        from bigdl_tpu.transform.vision import ImageFeature
        for s in it:
            feat = ImageFeature(s.feature)
            for t in self.stages:
                feat = t(feat)
            yield Sample(feat.image, s.label)


def _list_image_folder(path: str):
    """Lazy ImageNet listing: (file path, 1-based label) pairs — images
    decode inside the pipeline, never all-at-once in host RAM."""
    classes = sorted(d for d in os.listdir(path)
                     if os.path.isdir(os.path.join(path, d)))
    items = []
    for ci, cls in enumerate(classes):
        cdir = os.path.join(path, cls)
        items.extend((os.path.join(cdir, fn), ci + 1)
                     for fn in sorted(os.listdir(cdir)))
    return items, len(classes)


class _Decode:
    """(path, label) → Sample(HWC float32, label)."""

    def __call__(self, it):
        import numpy as np
        from PIL import Image
        from bigdl_tpu.dataset.dataset import Sample
        for path, label in it:
            img = np.asarray(Image.open(path).convert("RGB"), np.float32)
            yield Sample(img, label)


def _synthetic(n: int, size: int, classes: int, seed: int):
    """Per-class prototypes generated lazily from the label's own seed,
    so the full --classes head is honored without a classes-sized
    prototype tensor in RAM."""
    import numpy as np
    from bigdl_tpu.dataset.dataset import Sample
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, size=n)
    out = []
    for l in labels:
        proto = np.random.default_rng(10_000 + int(l)).normal(
            size=(size, size, 3))
        out.append(Sample((proto + 0.25 * rng.normal(
            size=(size, size, 3))).astype(np.float32), int(l) + 1))
    return out, classes


def main(argv=None):
    p = base_parser("Train ResNet-50 / Inception-v1 / VGG16 on ImageNet")
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "inception-v1", "vgg16"])
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--warmup-epochs", type=int, default=0)
    p.set_defaults(batch_size=256, learning_rate=0.1, max_epoch=90)
    args = p.parse_args(argv)
    train_summary, val_summary = setup(args, f"imagenet-{args.model}")

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim import (
        Loss, Optimizer, Poly, SGD, SequentialSchedule, Top1Accuracy,
        Top5Accuracy, Trigger, Warmup,
    )

    size = args.image_size
    val_data = None
    if args.synthetic:
        classes = args.classes
        train, _ = _synthetic(args.synthetic, size, classes, seed=0)
        val, _ = _synthetic(max(args.synthetic // 8, args.batch_size),
                            size, classes, seed=1)
        n_train = len(train)
        train_data = (DataSet.array(train)
                      .transform(SampleToMiniBatch(args.batch_size)))
        if args.cache_device:
            train_data = train_data.cache_on_device()
        val_data = (DataSet.array(val, shuffle=False)
                    .transform(SampleToMiniBatch(args.batch_size)))
    else:
        if args.cache_device:
            raise SystemExit(
                "--cache-device would freeze the random crops/flips of "
                "epoch 1 and replay them forever; it is only valid with "
                "--synthetic data")
        train_items, classes = _list_image_folder(
            os.path.join(args.folder, "train"))
        n_train = len(train_items)
        train_data = (DataSet.array(train_items)
                      .transform(_Decode())
                      .transform(_Augment(train=True, size=size))
                      .transform(SampleToMiniBatch(args.batch_size)))
        val_dir = os.path.join(args.folder, "val")
        if os.path.isdir(val_dir):
            val_items, _ = _list_image_folder(val_dir)
            val_data = (DataSet.array(val_items, shuffle=False)
                        .transform(_Decode())
                        .transform(_Augment(train=False, size=size))
                        .transform(SampleToMiniBatch(args.batch_size)))

    model = _build_model(args.model, classes)
    iters_per_epoch = max(n_train // args.batch_size, 1)
    total_iters = args.max_epoch * iters_per_epoch
    if args.warmup_epochs > 0:
        # linear ramp to the base lr over the warmup epochs, then Poly
        # (the reference's large-batch recipe, SGD.SequentialSchedule)
        warm_iters = args.warmup_epochs * iters_per_epoch
        schedule = (SequentialSchedule(iters_per_epoch)
                    .add(Warmup(args.learning_rate / warm_iters),
                         warm_iters)
                    .add(Poly(0.5, total_iters - warm_iters),
                         total_iters - warm_iters))
    else:
        schedule = Poly(0.5, total_iters)
    method = SGD(args.learning_rate, momentum=args.momentum,
                 dampening=0.0, weight_decay=args.weight_decay,
                 nesterov=True, learning_rate_schedule=schedule)
    opt = (Optimizer(model, train_data, nn.CrossEntropyCriterion())
           .set_optim_method(method)
           .set_end_when(Trigger.max_epoch(args.max_epoch)))
    if val_data is not None:
        methods = [Top1Accuracy(), Loss(nn.CrossEntropyCriterion())]
        if classes >= 5:
            methods.insert(1, Top5Accuracy())
        opt.set_validation(Trigger.every_epoch(), val_data, methods)
    apply_common(opt, args, train_summary, val_summary)
    opt.optimize()
    if val_data is not None:
        print(f"Final validation score: {opt.state['score']:.4f}")
    return model


if __name__ == "__main__":
    main()
