"""ResNet / CIFAR-10 training main (reference models/resnet/Train.scala
and the parameter table in models/resnet/README.md:63-78).

    bigdl-tpu-resnet-cifar -f /data/cifar10 --depth 20 -b 128 -e 10
    bigdl-tpu-resnet-cifar --synthetic 2048 -e 2
"""

from __future__ import annotations

from bigdl_tpu.examples.common import apply_common, base_parser, setup


def main(argv=None):
    p = base_parser("Train ResNet on CIFAR-10")
    p.add_argument("--depth", type=int, default=20,
                   help="6n+2 CIFAR ResNet depth (20/32/44/56/110)")
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.set_defaults(learning_rate=0.1)
    args = p.parse_args(argv)
    train_summary, val_summary = setup(args, "resnet-cifar")

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.cifar import cifar10_samples, synthetic_cifar10
    from bigdl_tpu.models import resnet_cifar
    from bigdl_tpu.optim import (
        Loss, MultiStep, Optimizer, SGD, Top1Accuracy, Trigger,
    )

    if args.synthetic:
        train, test = (synthetic_cifar10(args.synthetic, seed=0),
                       synthetic_cifar10(max(args.synthetic // 4, args.batch_size),
                                         seed=1))
    else:
        train = cifar10_samples(args.folder, train=True)
        test = cifar10_samples(args.folder, train=False)

    data = DataSet.array(train).transform(SampleToMiniBatch(args.batch_size))
    if args.cache_device:
        data = data.cache_on_device()
    model = resnet_cifar(depth=args.depth, class_num=10)
    # reference recipe: SGD momentum 0.9, lr/10 at epochs 80 and 120
    iters_per_epoch = max(len(train) // args.batch_size, 1)
    method = SGD(args.learning_rate, momentum=args.momentum, dampening=0.0,
                 weight_decay=args.weight_decay,
                 learning_rate_schedule=MultiStep(
                     [80 * iters_per_epoch, 120 * iters_per_epoch], 0.1))
    opt = (Optimizer(model, data, nn.CrossEntropyCriterion())
           .set_optim_method(method)
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_validation(Trigger.every_epoch(), test,
                           [Top1Accuracy(),
                            Loss(nn.CrossEntropyCriterion())],
                           batch_size=args.batch_size))
    apply_common(opt, args, train_summary, val_summary)
    opt.optimize()
    print(f"Final validation score: {opt.state['score']:.4f}")
    return model


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
