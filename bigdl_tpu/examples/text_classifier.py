"""Text classification main (reference example/textclassification:
20-newsgroups CNN over word embeddings, TextClassifier.scala).

    bigdl-tpu-textclassifier -f /data/20news -e 5      # class-per-subdir
    bigdl-tpu-textclassifier --synthetic 2000 -e 2

Data layout: one subdirectory per class, each holding text files
(the reference's 20news-18828 layout).
"""

from __future__ import annotations

import os

from bigdl_tpu.examples.common import apply_common, base_parser, setup


def build_model(vocab_size: int, class_num: int, seq_len: int,
                embed_dim: int = 128, filters: int = 128,
                kernel: int = 5):
    """Embedding → temporal CNN → max-over-time → MLP (the reference's
    TextClassifier CNN shape; GloVe init is replaced by trained
    embeddings — zero-egress environments cannot fetch GloVe)."""
    import bigdl_tpu.nn as nn
    pooled = (seq_len - kernel) + 1
    return nn.Sequential(
        nn.LookupTable(vocab_size, embed_dim),
        nn.TemporalConvolution(embed_dim, filters, kernel),
        nn.ReLU(),
        nn.TemporalMaxPooling(pooled),
        nn.Flatten(),
        nn.Linear(filters, 100),
        nn.ReLU(),
        nn.Linear(100, class_num),
        nn.LogSoftMax(),
    )


def load_folder(folder: str, seq_len: int, vocab_size: int):
    """Class-per-subdirectory text corpus → (samples, n_classes)."""
    import numpy as np
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.dataset.text import Dictionary, Tokenizer

    classes = sorted(d for d in os.listdir(folder)
                     if os.path.isdir(os.path.join(folder, d)))
    if not classes:
        raise SystemExit(f"no class subdirectories under {folder!r}")
    tok = Tokenizer()
    texts, labels = [], []
    for ci, cls in enumerate(classes):
        cdir = os.path.join(folder, cls)
        for fname in sorted(os.listdir(cdir)):
            path = os.path.join(cdir, fname)
            if not os.path.isfile(path):
                continue
            with open(path, errors="replace") as f:
                texts.append(f.read())
            labels.append(ci + 1)
    token_lists = [toks for toks in tok(iter(texts))]
    dictionary = Dictionary(token_lists, vocab_size=vocab_size)
    samples = []
    for toks, label in zip(token_lists, labels):
        ids = dictionary.indices(toks)[:seq_len]
        ids = ids + [dictionary.unk_index] * (seq_len - len(ids))
        samples.append(Sample(np.asarray(ids, np.int32), label))
    return samples, len(classes), dictionary


def synthetic_corpus(n: int, seq_len: int, vocab: int = 200,
                     n_classes: int = 4, seed: int = 0):
    """Per-class token distributions, separable but noisy."""
    import numpy as np
    from bigdl_tpu.dataset.dataset import Sample
    rng = np.random.default_rng(seed)
    class_words = rng.integers(1, vocab + 1, size=(n_classes, 8))
    samples = []
    for _ in range(n):
        c = int(rng.integers(0, n_classes))
        ids = np.where(rng.random(seq_len) < 0.5,
                       rng.choice(class_words[c], size=seq_len),
                       rng.integers(1, vocab + 1, size=seq_len))
        samples.append(Sample(ids.astype(np.int32), c + 1))
    return samples, n_classes


def main(argv=None):
    p = base_parser("Train a CNN text classifier")
    p.add_argument("--seq-len", type=int, default=200)
    p.add_argument("--vocab-size", type=int, default=20000)
    p.add_argument("--embed-dim", type=int, default=128)
    p.set_defaults(batch_size=32, learning_rate=0.05, max_epoch=5)
    args = p.parse_args(argv)
    train_summary, val_summary = setup(args, "textclassifier")

    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim import (
        Loss, Optimizer, SGD, Top1Accuracy, Trigger,
    )

    if args.synthetic:
        vocab = args.vocab_size
        samples, n_classes = synthetic_corpus(
            args.synthetic, args.seq_len, vocab=vocab)
    else:
        samples, n_classes, dictionary = load_folder(
            args.folder, args.seq_len, args.vocab_size)
        vocab = dictionary.vocab_size()

    rng = np.random.default_rng(42)
    order = rng.permutation(len(samples))
    split = max(int(0.8 * len(samples)), 1)
    train = [samples[i] for i in order[:split]]
    test = [samples[i] for i in order[split:]] or train[:args.batch_size]

    data = DataSet.array(train).transform(
        SampleToMiniBatch(args.batch_size))
    if args.cache_device:
        data = data.cache_on_device()
    model = build_model(vocab + 1, n_classes, args.seq_len,
                        embed_dim=args.embed_dim)
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_validation(Trigger.every_epoch(), test,
                           [Top1Accuracy(), Loss(nn.ClassNLLCriterion())],
                           batch_size=args.batch_size))
    apply_common(opt, args, train_summary, val_summary)
    opt.optimize()
    print(f"Final validation score: {opt.state['score']:.4f}")
    return model


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
