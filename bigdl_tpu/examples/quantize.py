"""int8 quantization CLI (reference example/mkldnn int8 conversion +
the whitepaper's quantized-inference recipe, docs/docs/whitepaper.md
179-196: local min/max windows, <0.1% accuracy drop, ~4x model-size
reduction).

    bigdl-tpu-quantize --model trained.bigdl --output quantized.bigdl
    bigdl-tpu-quantize --model trained.bigdl --evaluate <folder>/val

Loads a bigdl-format model, swaps Linear/SpatialConvolution layers for
int8 versions (``Quantizer.quantize``), optionally compares fp32 vs
int8 accuracy on an image folder, reports the parameter-bytes
reduction, and saves the quantized model.
"""

from __future__ import annotations

import argparse
import logging


def _param_bytes(model) -> int:
    import jax
    import numpy as np
    from bigdl_tpu.core.module import partition
    params, rest = partition(model)
    # int8 layers keep their weights in buffers (rest), so count both
    return sum(np.asarray(leaf).nbytes
               for tree in (params, rest)
               for leaf in jax.tree_util.tree_leaves(tree)
               if hasattr(leaf, "dtype"))


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Quantize a trained model to int8 inference form")
    p.add_argument("--model", required=True, help="bigdl-format model file")
    p.add_argument("--output", default=None,
                   help="where to save the quantized model")
    p.add_argument("--evaluate", default=None, metavar="FOLDER",
                   help="image folder: report fp32 vs int8 accuracy")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--workers", type=int, default=8,
                   help="decode threads for --evaluate")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.WARNING if args.quiet else logging.INFO)

    from bigdl_tpu.nn.quantized import Quantizer
    from bigdl_tpu.utils.serializer import load_module, save_module

    model = load_module(args.model).eval_mode()
    quantized = Quantizer.quantize(model)
    before, after = _param_bytes(model), _param_bytes(quantized)
    print(f"parameter bytes: {before} -> {after} "
          f"({before / max(after, 1):.2f}x reduction)")

    results = {"bytes_fp32": before, "bytes_int8": after}
    if args.evaluate:
        from bigdl_tpu.examples.imagenet import eval_pipeline
        from bigdl_tpu.examples.loadmodel import check_class_count
        from bigdl_tpu.optim.predictor import Evaluator
        from bigdl_tpu.optim.validation import Top1Accuracy
        data, classes, _ = eval_pipeline(
            args.evaluate, args.image_size, args.batch_size,
            workers=args.workers)
        check_class_count(model, classes, args.image_size)
        for tag, m in (("fp32", model), ("int8", quantized)):
            (res, _meth), = Evaluator(m, args.batch_size).evaluate(
                data, [Top1Accuracy()])
            results[f"top1_{tag}"] = res.result()[0]
            print(f"{tag} Top1Accuracy: {res.result()[0]:.4f}")
    if args.output:
        save_module(quantized, args.output)
        print(f"saved int8 model to {args.output}")
    return results


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
