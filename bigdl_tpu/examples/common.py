"""Shared CLI plumbing for the example mains (reference
models/*/Utils.scala scopt parsers)."""

from __future__ import annotations

import argparse
import logging


def _positive_int(s: str) -> int:
    n = int(s)
    if n <= 0:
        raise argparse.ArgumentTypeError(f"must be positive, got {n}")
    return n


def base_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--folder", default=None,
                   help="dataset directory (omit with --synthetic)")
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("-e", "--max-epoch", type=int, default=5)
    p.add_argument("-r", "--learning-rate", type=float, default=0.05)
    p.add_argument("--checkpoint", default=None,
                   help="directory for per-epoch checkpoints")
    p.add_argument("--keep-checkpoints", type=_positive_int, default=None,
                   metavar="N",
                   help="keep the newest N good checkpoint generations "
                        "(numbered checkpoints + retention GC; default: "
                        "one overwritten checkpoint file)")
    p.add_argument("--state", default=None,
                   help="checkpoint file to resume from")
    p.add_argument("--summary-dir", default=None,
                   help="TensorBoard event-file directory")
    p.add_argument("--synthetic", type=_positive_int, default=None,
                   metavar="N",
                   help="train on N synthetic samples instead of files")
    p.add_argument("--bf16", action="store_true",
                   help="bfloat16 compute with fp32 master weights")
    p.add_argument("--cache-device", action="store_true",
                   help="cache the dataset in device memory (HBM)")
    p.add_argument("--device-prefetch", type=_positive_int, default=None,
                   metavar="N",
                   help="stage batch N+1 to device while step N runs "
                        "(async double-buffered H2D; see "
                        "docs/data_pipeline.md)")
    p.add_argument("-q", "--quiet", action="store_true")
    return p


def setup(args, app_name: str):
    """Logging + summaries; returns (train_summary, val_summary)."""
    logging.basicConfig(
        level=logging.WARNING if args.quiet else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s - %(message)s")
    # multi-host bootstrap (no-op off-pod; ≙ the reference's cluster
    # Engine.init): must run before any backend use so every host sees
    # the global device set
    from bigdl_tpu.utils import Engine
    Engine.init_distributed()
    if not args.folder and args.synthetic is None:
        raise SystemExit(
            f"{app_name}: provide --folder DATA_DIR or --synthetic N")
    if args.summary_dir:
        from bigdl_tpu.visualization import TrainSummary, ValidationSummary
        return (TrainSummary(args.summary_dir, app_name),
                ValidationSummary(args.summary_dir, app_name))
    return None, None


def apply_common(opt, args, train_summary=None, val_summary=None):
    """Wire the flags every example shares into the Optimizer."""
    from bigdl_tpu.optim import Trigger
    if args.bf16:
        import jax.numpy as jnp
        opt.set_compute_dtype(jnp.bfloat16)
    if args.checkpoint:
        opt.set_checkpoint(args.checkpoint, Trigger.every_epoch(),
                           keep_n=args.keep_checkpoints)
    if getattr(args, "device_prefetch", None):
        opt.set_device_prefetch(args.device_prefetch)
    if args.state:
        opt.resume(args.state)
    if train_summary is not None:
        opt.set_train_summary(train_summary)
    if val_summary is not None:
        opt.set_val_summary(val_summary)
    return opt
