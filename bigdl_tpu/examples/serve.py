"""Model-serving CLI over HTTP (reference example/udfpredictor — model
serving behind Spark SQL UDFs — rebuilt on PredictionService, the
reference's thread-safe concurrent inference pool,
optim/PredictionService.scala:56-129).

    bigdl-tpu-serve --model trained.bigdl --port 8500

Protocol (stdlib-only on both ends):

* ``POST /predict`` with an ``.npy``-serialized array body →
  ``.npy``-serialized output array (``application/octet-stream``).
* ``POST /generate`` (with ``--generate MAX_NEW``) with a JSON body
  ``{"prompt": [token ids], "max_new_tokens": n, "eos_id": t}`` →
  ``{"tokens": [...]}`` — greedy continuation through the
  continuous-batching KV slot pool (``bigdl_tpu.serving.generation``):
  concurrent HTTP generations share decode iterations mid-flight
  instead of serializing.
* ``GET /healthz`` → ``{"status": "ok"}``, or **503**
  ``{"status": "draining"}`` once shutdown has begun — a load balancer
  keeps routing to a replica that answers 200, so a draining one must
  stop saying "ok" while it finishes its in-flight work.
* ``GET /metrics`` → Prometheus text exposition from the unified
  ``bigdl_tpu.telemetry`` registry: serving latency quantiles, queue
  depth, batch occupancy — plus every optimizer/checkpoint family (one
  scrape config covers training and serving roles; see
  docs/observability.md).
* ``GET /statusz`` / ``GET /tracez`` / ``POST /profilez`` — live
  introspection (status page, recent spans, on-demand time-boxed
  ``jax.profiler`` capture returning its logdir); see
  docs/observability.md "Health & introspection".

Client::

    buf = io.BytesIO(); np.save(buf, x)
    conn = http.client.HTTPConnection("localhost", 8500)
    conn.request("POST", "/predict", buf.getvalue())
    y = np.load(io.BytesIO(conn.getresponse().read()))
"""

from __future__ import annotations

import argparse
import json
import logging
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger("bigdl_tpu.serve")


class BatchedBytesFrontend:
    """Adapter giving a ``bigdl_tpu.serving.ModelServer`` the same
    ``predict_bytes`` surface as PredictionService: each request body is
    ONE npy-serialized sample (no batch axis), and concurrent HTTP
    threads coalesce into padded device batches via the dynamic
    batcher."""

    def __init__(self, server):
        self._server = server

    def predict_bytes(self, payload: bytes) -> bytes:
        from bigdl_tpu.optim.predictor import npy_call_bytes
        return npy_call_bytes(self._server.submit, payload)


class GenerateJsonFrontend:
    """JSON adapter for the continuous-batching generation engine: one
    request body in, the full greedy token row out.  ``max_new_cap``
    bounds the per-request decode budget a client may ask for."""

    def __init__(self, server, max_new_cap: int):
        self._server = server
        self.max_new_cap = int(max_new_cap)

    def generate_bytes(self, payload: bytes) -> bytes:
        doc = json.loads(payload.decode("utf-8"))
        prompt = doc["prompt"]
        max_new = int(doc.get("max_new_tokens", self.max_new_cap))
        if not (1 <= max_new <= self.max_new_cap):
            raise ValueError(
                f"max_new_tokens must be in [1, {self.max_new_cap}]")
        row = self._server.submit_generate(
            prompt, max_new, eos_id=doc.get("eos_id"))
        return json.dumps({"tokens": [int(t) for t in row]}).encode()


def make_server(service, host: str, port: int,
                statusz_fn=None, generate_frontend=None
                ) -> ThreadingHTTPServer:
    """ThreadingHTTPServer wired to a PredictionService; concurrency is
    bounded by the service's ticket pool, not the HTTP threads.  The
    returned server carries ``health_state`` (flip ``["draining"]`` to
    make ``/healthz`` answer 503) and ``debugz`` (the
    /statusz|/tracez|/profilez logic; its ``statusz_fn`` may be set
    after construction)."""
    from bigdl_tpu.telemetry.debugz import Debugz, DebugzHandlerMixin

    class Handler(DebugzHandlerMixin, BaseHTTPRequestHandler):
        def log_message(self, fmt, *fargs):
            logger.info("%s " + fmt, self.address_string(), *fargs)

        def _reply(self, code: int, body: bytes,
                   ctype: str = "application/octet-stream"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.handle_debugz("GET"):
                return
            if self.path == "/healthz":
                if self.server.health_state.get("draining"):
                    # non-200: the LB must stop routing here while the
                    # in-flight batches finish
                    self._reply(503, json.dumps(
                        {"status": "draining"}).encode(),
                        "application/json")
                else:
                    self._reply(200,
                                json.dumps({"status": "ok"}).encode(),
                                "application/json")
            elif self.path == "/metrics":
                from bigdl_tpu.telemetry import prometheus_text
                self._reply(200, prometheus_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
            else:
                self._reply(404, b"not found", "text/plain")

        def do_POST(self):
            if self.handle_debugz("POST"):
                return
            if self.path == "/generate":
                if generate_frontend is None:
                    self._reply(404, json.dumps(
                        {"error": "generation not enabled; start with "
                                  "--generate MAX_NEW"}).encode(),
                        "application/json")
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = self.rfile.read(n)
                    self._reply(200,
                                generate_frontend.generate_bytes(payload),
                                "application/json")
                except Exception as e:  # noqa: BLE001 — client-facing
                    self._reply(400, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")
                return
            if self.path != "/predict":
                self._reply(404, b"not found", "text/plain")
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = self.rfile.read(n)
                self._reply(200, service.predict_bytes(payload))
            except Exception as e:  # noqa: BLE001 — client-facing error
                self._reply(400, json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}).encode(),
                    "application/json")

    server = ThreadingHTTPServer((host, port), Handler)
    server.health_state = {"draining": False}
    server.debugz = Debugz(statusz_fn=statusz_fn)
    return server


def main(argv=None):
    p = argparse.ArgumentParser(description="Serve a model over HTTP")
    p.add_argument("--model", required=True, help="bigdl-format model file")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--concurrency", type=int, default=4,
                   help="max in-flight predictions")
    p.add_argument("--dynamic-batch", type=int, default=None,
                   metavar="MAX_BATCH",
                   help="coalesce concurrent requests into padded "
                        "device batches (bigdl_tpu.serving); each POST "
                        "body is then ONE sample without a batch axis")
    p.add_argument("--batch-timeout-ms", type=float, default=5.0,
                   help="max wait before a partial batch is served "
                        "(only with --dynamic-batch)")
    p.add_argument("--generate", type=int, default=None, metavar="MAX_NEW",
                   help="enable POST /generate: continuous-batching "
                        "greedy decoding over the loaded model's KV "
                        "slot pool, at most MAX_NEW tokens per request "
                        "(the model must expose the incremental-decode "
                        "API, e.g. TransformerLM)")
    p.add_argument("--slots", type=int, default=8,
                   help="KV slot-pool width for --generate")
    p.add_argument("--fleet-dir", default=None, metavar="DIR",
                   help="publish this replica's health snapshot into "
                        "DIR via the fleet file transport so a serving-"
                        "fabric Router (bigdl_tpu.serving.router) can "
                        "route to / drain this process; the snapshot "
                        "carries the /healthz drain state")
    p.add_argument("--replica-id", type=int, default=0,
                   help="fleet snapshot id under --fleet-dir (one per "
                        "replica process)")
    p.add_argument("--no-telemetry", action="store_true",
                   help="disable the unified telemetry registry (the "
                        "/metrics endpoint then exposes an empty "
                        "catalog)")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.WARNING if args.quiet else logging.INFO)

    # serving enables telemetry by default: the scrape endpoint is the
    # reason this process exists to an SRE, and the serving hot path
    # only pays pull-time collection (docs/observability.md).  The flag
    # must actively disable — BIGDL_TPU_TELEMETRY=1 in the environment
    # enables at import, and skipping enable() would not undo that.
    from bigdl_tpu import telemetry
    if args.no_telemetry:
        # disable AND clear: BIGDL_TPU_TELEMETRY=1 enables at import,
        # which preregisters the catalog — without the clear, /metrics
        # would still expose every family at zero
        telemetry.disable()
        telemetry.get_registry().clear()
        telemetry.reset_spans()
    else:
        telemetry.enable()

    from bigdl_tpu.optim.predictor import PredictionService
    from bigdl_tpu.utils.serializer import load_module

    loaded = load_module(args.model)
    service = PredictionService(loaded, concurrency=args.concurrency)
    batcher = None
    if args.dynamic_batch is not None:
        # bucket_sizes rejects 0/negative rather than silently ignoring
        batcher = service.serve(max_batch=args.dynamic_batch,
                                batch_timeout_ms=args.batch_timeout_ms)
        service = BatchedBytesFrontend(batcher)
    gen_server = None
    gen_frontend = None
    if args.generate is not None:
        from bigdl_tpu.serving import ModelServer
        gen_server = ModelServer(generator=loaded, slots=args.slots)
        gen_frontend = GenerateJsonFrontend(gen_server, args.generate)
    server = make_server(service, args.host, args.port,
                         generate_frontend=gen_frontend)

    def _statusz():
        info = {"role": "server", "model": args.model,
                "dynamic_batch": args.dynamic_batch,
                "draining": server.health_state.get("draining", False)}
        if batcher is not None:
            info["queue_depth"] = batcher.queue_depth()
        if gen_server is not None:
            info["generation"] = gen_server.generation_stats()
        return info

    server.debugz.statusz_fn = _statusz
    publisher = None
    if args.fleet_dir:
        # the replica side of the serving fabric: drop a periodic
        # health snapshot (queue depth, slot occupancy, TTFT p99,
        # draining flag) for the router's registry — the same file a
        # Replica handle would write, so drain/deploy sees this
        # process exactly like an in-process replica
        from bigdl_tpu.serving.replica import (
            SnapshotPublisher, replica_snapshot,
        )
        from bigdl_tpu.telemetry.fleet import write_host_snapshot

        # incarnation stamp, taken once at process start: a restart
        # under the same --replica-id publishes a strictly larger
        # generation, so the registry can tell the new life's
        # snapshots from the dying publisher's final (draining) write
        # racing them — without it, that stale write masks the
        # restarted replica (ReplicaRegistry.poll rewarming)
        start_generation = int(time.time() * 1000)

        def _publish_snapshot():
            write_host_snapshot(args.fleet_dir, replica_snapshot(
                args.replica_id, gen_server or batcher,
                name=f"serve-{args.replica_id}", role="mixed",
                draining=bool(server.health_state.get("draining")),
                start_generation=start_generation))

        publisher = SnapshotPublisher(_publish_snapshot,
                                      interval_s=0.25)
    logger.info("serving on %s:%d", args.host, server.server_port)
    # SIGTERM (the orchestrator's stop notice) takes the same graceful
    # path as Ctrl-C: unwind serve_forever, then drain the batcher so
    # in-flight batched requests complete before the process exits
    # (mirrors the training loop's preemption handling)
    import signal

    def _sigterm(signum, frame):
        logger.info("signal %d: shutting down, draining in-flight "
                    "requests", signum)
        raise KeyboardInterrupt

    try:
        prev_term = signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:  # non-main thread (tests): keep default handling
        prev_term = None
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # shutdown has begun: from here /healthz answers 503 draining,
        # so the load balancer stops routing to this replica while the
        # already-admitted requests finish
        server.health_state["draining"] = True
        if publisher is not None:
            # the router registry must see draining:true BEFORE the
            # drain starts, not one publish interval into it
            publisher.publish_now()
        if batcher is not None or gen_server is not None:
            # keep answering HTTP (now-503 health checks, in-flight
            # predicts/generates) on a background accept loop while the
            # batcher and the slot pool drain: the documented drain
            # answers every queued request — and finishes every
            # mid-decode generation — before the scheduler threads exit
            import threading

            t = threading.Thread(target=server.serve_forever,
                                 daemon=True, name="bigdl-serve-drain")
            t.start()
            if batcher is not None:
                batcher.shutdown(drain=True)
            if gen_server is not None:
                gen_server.shutdown(drain=True)
            server.shutdown()
            t.join(timeout=10.0)
        server.server_close()
        if publisher is not None:
            # the draining state was already published when the flag
            # flipped; on exit the snapshot is REMOVED so the registry
            # forgets this replica instead of reporting a dead ghost
            # as stale forever
            publisher.stop(final_publish=False)
            from bigdl_tpu.telemetry.fleet import remove_host_snapshot
            remove_host_snapshot(args.fleet_dir, args.replica_id)
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
    return server


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
