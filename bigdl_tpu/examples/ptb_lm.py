"""PTB word-level language model main (reference
example/languagemodel/PTBWordLM.scala; ``--model transformer`` swaps the
LSTM for the decoder-only Transformer LM, the reference
nn/Transformer.scala LanguageModel configuration).

    bigdl-tpu-ptb -f /data/ptb -b 32 -e 13          # real Penn Treebank
    bigdl-tpu-ptb --synthetic 40000 -e 2            # Markov-chain corpus
    bigdl-tpu-ptb --synthetic 40000 --model transformer --remat
"""

from __future__ import annotations

import math

from bigdl_tpu.examples.common import apply_common, base_parser, setup


def main(argv=None):
    p = base_parser("Train the PTB word-level LSTM LM")
    p.add_argument("--vocab-size", type=int, default=10000)
    p.add_argument("--hidden-size", type=int, default=200)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-steps", type=int, default=20)
    p.add_argument("--model", default="lstm",
                   choices=["lstm", "transformer"])
    p.add_argument("--num-heads", type=int, default=4,
                   help="attention heads (transformer)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize transformer blocks (saves HBM)")
    p.set_defaults(batch_size=32, learning_rate=1.0, max_epoch=13)
    args = p.parse_args(argv)
    train_summary, val_summary = setup(args, "ptb")

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, MiniBatch
    from bigdl_tpu.dataset.text import (
        load_ptb_corpus, ptb_batches, synthetic_ptb,
    )
    from bigdl_tpu.models import PTBModel
    from bigdl_tpu.optim import Loss, Optimizer, SGD, Trigger

    if args.synthetic:
        vocab = min(args.vocab_size, 1000)
        train_ids = synthetic_ptb(args.synthetic, vocab=vocab, seed=0)
        # enough words for at least one [batch, num_steps] window
        valid_n = max(args.synthetic // 4,
                      args.batch_size * (args.num_steps + 1) + 1)
        valid_ids = synthetic_ptb(valid_n, vocab=vocab, seed=1)
    else:
        train_ids, valid_ids, _test_ids, dictionary = load_ptb_corpus(
            args.folder, vocab_size=args.vocab_size)
        vocab = dictionary.vocab_size()

    def to_dataset(ids, shuffle):
        batches = [MiniBatch(x, y) for x, y in
                   ptb_batches(ids, args.batch_size, args.num_steps)]
        return DataSet.array(batches, shuffle=shuffle)

    data = to_dataset(train_ids, shuffle=True)
    if args.cache_device:
        data = data.cache_on_device()
    val_data = to_dataset(valid_ids, shuffle=False)

    if args.model == "transformer":
        from bigdl_tpu.models import transformer_lm
        lm = transformer_lm(vocab_size=vocab,
                            hidden_size=args.hidden_size,
                            num_layers=args.num_layers,
                            num_heads=args.num_heads,
                            filter_size=4 * args.hidden_size,
                            max_len=args.num_steps,
                            remat=args.remat)
        # logits -> per-step log-probs, matching the LSTM head so the
        # same TimeDistributedCriterion drives both models
        model = nn.Sequential(lm, nn.LogSoftMax())
    else:
        model = PTBModel(input_size=vocab + 1,
                         hidden_size=args.hidden_size,
                         output_size=vocab + 1,
                         num_layers=args.num_layers)
    criterion = nn.TimeDistributedCriterion(
        nn.ClassNLLCriterion(), size_average=False, dimension=2)
    opt = (Optimizer(model, data, criterion)
           .set_optim_method(SGD(args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_gradient_clipping_by_l2_norm(5.0)
           .set_validation(Trigger.every_epoch(), val_data,
                           [Loss(criterion)]))
    apply_common(opt, args, train_summary, val_summary)
    opt.optimize()
    val_loss = opt.state["score"]
    per_word = val_loss / args.num_steps  # criterion sums over timesteps
    print(f"Final validation loss {val_loss:.4f} "
          f"(perplexity {math.exp(min(per_word, 20.0)):.2f})")
    return model


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
