"""Model-import validator CLI (reference example/loadmodel: load an
AlexNet/Inception model from Caffe/Torch/BigDL format and validate or
predict with it).

    bigdl-tpu-loadmodel --format bigdl  --model m.bigdl  --predict img.jpg
    bigdl-tpu-loadmodel --format caffe  --prototxt d.prototxt \
        --model w.caffemodel --evaluate <folder>/val
    bigdl-tpu-loadmodel --format torch  --model m.t7 --predict img.jpg

``--evaluate`` expects a class-per-subdirectory image folder and prints
Top-1/Top-5 accuracy; ``--predict`` prints the top-5 (index, score)
pairs per image.  Indices are 1-based like every label in the
framework.
"""

from __future__ import annotations

import argparse
import logging


def load_model(fmt: str, model_path: str, prototxt: str = None):
    """Load a module from any supported interop format."""
    if fmt == "bigdl":
        from bigdl_tpu.utils.serializer import load_module
        return load_module(model_path)
    if fmt == "caffe":
        if not prototxt:
            raise SystemExit("--format caffe requires --prototxt")
        from bigdl_tpu.interop.caffe import load_caffe
        return load_caffe(prototxt, model_path)
    if fmt == "torch":
        from bigdl_tpu.interop.torch_file import load_torch_module
        return load_torch_module(model_path)
    raise SystemExit(f"unknown --format {fmt!r}")


def _prep_images(paths, size):
    """Decode + eval-augment via the single shared _Augment path."""
    import numpy as np
    from bigdl_tpu.examples.imagenet import (_Augment, _decode_rgb,
                                             _short_side)
    aug = _Augment(train=False, size=size)
    ms = _short_side(size)
    return np.stack([aug.apply_one(_decode_rgb(p, ms)) for p in paths])


def check_class_count(model, folder_classes: int, size: int) -> None:
    """Warn when the evaluate folder's class-directory count disagrees
    with the model's output width: labels are assigned by sorted
    directory order, so a subset/superset folder silently renumbers
    classes and scores garbage (see _list_image_folder's docstring)."""
    import numpy as np
    try:
        probe = np.zeros((1, size, size, 3), np.float32)
        width = int(np.asarray(model.forward(probe)).shape[-1])
    except Exception:
        return  # non-image or shape-incompatible model: nothing to check
    if width != folder_classes:
        logging.warning(
            "evaluate folder has %d class directories but the model "
            "outputs %d classes — labels follow sorted directory order, "
            "so accuracy is only meaningful if the folder holds ALL "
            "model classes", folder_classes, width)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Load a Caffe/Torch/BigDL model; predict or evaluate")
    p.add_argument("--format", required=True,
                   choices=["bigdl", "caffe", "torch"])
    p.add_argument("--model", required=True, help="weights/model file")
    p.add_argument("--prototxt", default=None,
                   help="network definition (caffe format)")
    p.add_argument("--predict", nargs="+", default=None, metavar="IMAGE",
                   help="image files to classify")
    p.add_argument("--evaluate", default=None, metavar="FOLDER",
                   help="class-per-subdirectory folder to score")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--workers", type=int, default=8,
                   help="decode threads for --evaluate")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.WARNING if args.quiet else logging.INFO)
    if not args.predict and not args.evaluate:
        p.error("provide --predict IMAGE... or --evaluate FOLDER")

    model = load_model(args.format, args.model, args.prototxt)
    model.eval_mode()

    results = {}
    if args.predict:
        import numpy as np
        for start in range(0, len(args.predict), args.batch_size):
            chunk = args.predict[start:start + args.batch_size]
            out = np.asarray(model.forward(
                _prep_images(chunk, args.image_size)))
            if out.ndim == 1:
                out = out[None]
            for path, row in zip(chunk, out):
                top = np.argsort(row)[::-1][:5]
                pairs = [(int(i) + 1, float(row[i])) for i in top]
                results[path] = pairs
                print(path, " ".join(f"{c}:{s:.4f}" for c, s in pairs))
    if args.evaluate:
        from bigdl_tpu.examples.imagenet import eval_pipeline
        from bigdl_tpu.optim.predictor import Evaluator
        from bigdl_tpu.optim.validation import Loss, Top1Accuracy, \
            Top5Accuracy
        import bigdl_tpu.nn as nn
        data, classes, _ = eval_pipeline(
            args.evaluate, args.image_size, args.batch_size,
            workers=args.workers)
        check_class_count(model, classes, args.image_size)
        methods = [Top1Accuracy(), Loss(nn.CrossEntropyCriterion())]
        if classes >= 5:
            methods.insert(1, Top5Accuracy())
        for res, meth in Evaluator(model, args.batch_size).evaluate(
                data, methods):
            results[meth.fmt] = res.result()[0]
            print(f"{meth.fmt}: {res.result()[0]:.4f}")
    return results


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
