"""Tree-LSTM sentiment classification CLI (reference
example/treeLSTMSentiment: BinaryTreeLSTM over constituency trees on
the Stanford Sentiment Treebank).

    bigdl-tpu-treelstm -f /data/sst -e 5          # SST s-expression files
    bigdl-tpu-treelstm --synthetic 512 -e 2       # random trees

File layout for ``-f``: ``train.txt`` (and optional ``dev.txt``), one
PTB-style s-expression per line — ``(3 (2 It) (4 (2 's) (4 good)))`` —
with 0-4 sentiment labels at every node; the ROOT label is the
training target (5 classes, stored 1-based like every label here).

Trees are flattened post-order into static-shape arrays — the
tpu-friendly encoding consumed by ``nn.BinaryTreeLSTM``: per node a
``(left, right)`` child-index pair (−1,−1 for leaves) and a
``leaf_id`` into the token sequence (−1 for internal nodes); padding
slots carry the previous state forward so the ROOT always lands in the
last slot regardless of tree size.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from bigdl_tpu.examples.common import apply_common, base_parser, setup


def parse_sexpr(line: str):
    """One SST s-expression → (root_label 0-4, tokens, nodes) where
    nodes is a post-order list of (left, right, leaf_pos)."""
    pos = 0

    def parse() -> Tuple[int, int]:
        """Returns (node_index, label); appends to nodes/tokens."""
        nonlocal pos
        assert line[pos] == "(", f"expected '(' at {pos} in {line!r}"
        pos += 1
        label_start = pos
        while line[pos] not in " \t":
            pos += 1
        label = int(line[label_start:pos])
        pos += 1
        if line[pos] == "(":  # internal: exactly two children (SST)
            left, _ = parse()
            while line[pos] in " \t":
                pos += 1
            right, _ = parse()
            while pos < len(line) and line[pos] in " \t":
                pos += 1
            assert line[pos] == ")", f"expected ')' at {pos}"
            pos += 1
            nodes.append((left, right, -1))
        else:  # leaf: a token
            tok_start = pos
            while line[pos] != ")":
                pos += 1
            tokens.append(line[tok_start:pos].strip())
            pos += 1
            nodes.append((-1, -1, len(tokens) - 1))
        return len(nodes) - 1, label

    tokens: List[str] = []
    nodes: List[Tuple[int, int, int]] = []
    line = line.strip()
    _, root_label = parse()
    return root_label, tokens, nodes


def trees_to_arrays(parsed, vocab: dict, n_nodes: int, n_tokens: int):
    """Parsed trees → (token_ids (B,T), children (B,N,2),
    leaf_ids (B,N), labels (B,)) with per-tree padding; trees larger
    than the budget are skipped."""
    toks_b, ch_b, leaf_b, y_b = [], [], [], []
    unk = len(vocab) + 1
    for root_label, tokens, nodes in parsed:
        if len(nodes) > n_nodes or len(tokens) > n_tokens:
            continue
        tok_ids = np.zeros(n_tokens, np.int32)  # 0 = padding id
        for i, t in enumerate(tokens):
            tok_ids[i] = vocab.get(t.lower(), unk)
        ch = np.full((n_nodes, 2), -1, np.int32)
        leaf = np.full(n_nodes, -1, np.int32)
        for i, (l, r, lp) in enumerate(nodes):
            ch[i] = (l, r)
            leaf[i] = lp
        toks_b.append(tok_ids)
        ch_b.append(ch)
        leaf_b.append(leaf)
        y_b.append(root_label + 1)  # 1-based labels
    if not toks_b:
        raise SystemExit("no trees fit --max-nodes/--max-tokens")
    return (np.stack(toks_b), np.stack(ch_b), np.stack(leaf_b),
            np.asarray(y_b, np.int32))


def build_model(vocab_size: int, dim: int, hidden: int, classes: int):
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.module import Module

    class TreeSentiment(Module):
        """embedding → BinaryTreeLSTM → root hidden → classifier."""

        def __init__(self):
            super().__init__()
            self.embedding = nn.LookupTable(vocab_size + 2, dim)
            self.tree = nn.BinaryTreeLSTM(dim, hidden)
            self.classifier = nn.Linear(hidden, classes)
            self.log_softmax = nn.LogSoftMax()

        def forward(self, inputs):
            tokens, children, leaf_ids = inputs
            # shift: LookupTable ids are 1-based, 0 is padding → map
            # padding to a real (unused) slot to keep gather in range
            x = self.embedding.forward(jnp.maximum(tokens, 1))
            h = self.tree.forward((x, children, leaf_ids))
            return self.log_softmax.forward(
                self.classifier.forward(h[:, -1]))

    return TreeSentiment()


def _synthetic_trees(n: int, vocab: int, n_nodes: int, seed: int):
    """Random full binary trees whose root label is decided by which
    token id range dominates the leaves — learnable signal."""
    rng = np.random.default_rng(seed)
    parsed = []
    for _ in range(n):
        n_leaves = int(rng.integers(3, (n_nodes + 1) // 2))
        cls = int(rng.integers(0, 5))
        # tokens biased towards the class's id bucket
        bucket = np.arange(cls * (vocab // 5), (cls + 1) * (vocab // 5))
        toks = [f"w{rng.choice(bucket)}"
                if rng.random() < 0.8 else f"w{rng.integers(0, vocab)}"
                for _ in range(n_leaves)]
        # left-leaning chain tree in post-order
        nodes = [(-1, -1, 0)]
        for i in range(1, n_leaves):
            nodes.append((-1, -1, i))          # leaf i
            nodes.append((len(nodes) - 2, len(nodes) - 1, -1))
        parsed.append((cls, toks, nodes))
    return parsed


def main(argv=None):
    p = base_parser("Tree-LSTM sentiment classification (SST)")
    p.add_argument("--embedding-dim", type=int, default=64)
    p.add_argument("--hidden-size", type=int, default=64)
    p.add_argument("--max-nodes", type=int, default=128)
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--vocab-size", type=int, default=2000)
    p.set_defaults(batch_size=32, learning_rate=0.05, max_epoch=5)
    args = p.parse_args(argv)
    if args.synthetic is not None:
        if args.max_nodes < 7:
            p.error("--synthetic needs --max-nodes >= 7 "
                    "(smallest random tree uses 3 leaves = 5 nodes)")
        if args.vocab_size < 5:
            p.error("--synthetic needs --vocab-size >= 5 "
                    "(one token-id bucket per sentiment class)")
    train_summary, val_summary = setup(args, "treelstm-sentiment")

    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.dataset import DataSet, MiniBatch
    from bigdl_tpu.optim import Optimizer, Top1Accuracy, Trigger
    from bigdl_tpu.optim.methods import Adagrad
    from bigdl_tpu.utils import set_seed

    set_seed(1)
    val_parsed = None
    if args.synthetic is not None:
        parsed = _synthetic_trees(args.synthetic, args.vocab_size,
                                  args.max_nodes, seed=0)
    else:
        import os
        with open(os.path.join(args.folder, "train.txt")) as f:
            parsed = [parse_sexpr(ln) for ln in f if ln.strip()]
        dev = os.path.join(args.folder, "dev.txt")
        if os.path.exists(dev):
            with open(dev) as f:
                val_parsed = [parse_sexpr(ln) for ln in f if ln.strip()]

    vocab: dict = {}
    for _, tokens, _ in parsed:
        for t in tokens:
            t = t.lower()
            if t not in vocab and len(vocab) < args.vocab_size:
                vocab[t] = len(vocab) + 1  # 1-based

    def batches(trees):
        toks, ch, leaf, y = trees_to_arrays(
            trees, vocab, args.max_nodes, args.max_tokens)
        out = []
        for i in range(0, len(y) - args.batch_size + 1, args.batch_size):
            s = slice(i, i + args.batch_size)
            out.append(MiniBatch((toks[s], ch[s], leaf[s]), y[s]))
        if not out:  # fewer trees than one batch: single ragged batch
            out = [MiniBatch((toks, ch, leaf), y)]
        return out

    data = DataSet.array(batches(parsed))
    if args.cache_device:
        data = data.cache_on_device()
    model = build_model(len(vocab), args.embedding_dim,
                        args.hidden_size, classes=5)
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(Adagrad(args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch)))
    if val_parsed:
        opt.set_validation(Trigger.every_epoch(),
                           DataSet.array(batches(val_parsed),
                                         shuffle=False),
                           [Top1Accuracy()])
    apply_common(opt, args, train_summary, val_summary)
    return opt.optimize()


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
