"""Neural Collaborative Filtering on MovieLens.

The recommender slice of the reference: HitRatio/NDCG leave-one-out
evaluation (optim/ValidationMethod.scala:883,950 — 1 positive scored
against ``--neg-eval`` unseen negatives, positive in column 0) over the
MovieLens id pairs (pyspark/bigdl/dataset/movielens.py).

    bigdl-tpu-ncf --synthetic 800 -e 4 -r 0.002
    bigdl-tpu-ncf -f /data/movielens -b 256 -e 10 -r 0.001
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.examples.common import apply_common, base_parser, setup


def leave_one_out(ratings: np.ndarray, neg_train: int, neg_eval: int,
                  seed: int = 0):
    """Split (user,item,rating,ts) rows into NCF training pairs and
    HitRatio evaluation rows.

    Per user the LAST interaction (by timestamp) is held out; training
    gets the rest as positives plus ``neg_train`` sampled unseen items
    per positive (label 0); evaluation rows are [1+neg_eval, 2] id
    pairs with the held-out positive first."""
    rng = np.random.default_rng(seed)
    n_items = int(ratings[:, 1].max())
    by_user: dict = {}
    for u, i, _r, ts in ratings:
        by_user.setdefault(int(u), []).append((int(ts), int(i)))

    train_pairs, train_labels, eval_rows = [], [], []
    for u, events in by_user.items():
        events.sort()
        items = [i for _, i in events]
        seen = set(items)
        holdout = items[-1]
        # negatives come from the user's UNSEEN items
        unseen = np.setdiff1d(np.arange(1, n_items + 1),
                              np.fromiter(seen, dtype=np.int64))
        if len(items) < 2 or len(unseen) == 0:
            continue
        for i in items[:-1]:
            train_pairs.append((u, i))
            train_labels.append(1.0)
            for j in rng.choice(unseen, size=neg_train, replace=True):
                train_pairs.append((u, int(j)))
                train_labels.append(0.0)
        # Eval rows must be one fixed shape ([1+neg_eval, 2]) for the
        # stacked batch, so a heavy user whose unseen pool is smaller
        # than neg_eval cannot simply get fewer negatives.  Take every
        # distinct unseen item first and only pad the remainder with
        # repeats — the maximum-distinct choice; the duplicates only
        # make the 1-vs-N rank STRICTER than the reference protocol,
        # never easier (acceptable for the synthetic smoke runs; real
        # MovieLens pools are ≫ neg_eval so this branch never pads).
        if len(unseen) >= neg_eval:
            negs = rng.choice(unseen, size=neg_eval, replace=False)
        else:
            pad = rng.choice(unseen, size=neg_eval - len(unseen),
                             replace=True)
            negs = np.concatenate([rng.permutation(unseen), pad])
        eval_rows.append(np.asarray(
            [(u, holdout)] + [(u, int(j)) for j in negs], dtype=np.int32))
    return (np.asarray(train_pairs, dtype=np.int32),
            np.asarray(train_labels, dtype=np.float32),
            np.stack(eval_rows))


def main(argv=None):
    p = base_parser("Train NCF (NeuMF) on MovieLens implicit feedback")
    p.add_argument("--embed-dim", type=int, default=16)
    p.add_argument("--neg-train", type=int, default=4,
                   help="sampled negatives per training positive")
    p.add_argument("--neg-eval", type=int, default=100,
                   help="negatives per held-out positive (HitRatio@k)")
    p.add_argument("--topk", type=int, default=10)
    args = p.parse_args(argv)
    train_summary, val_summary = setup(args, "ncf")

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.movielens import (
        read_data_sets, synthetic_ratings,
    )
    from bigdl_tpu.models.ncf import NeuralCF
    from bigdl_tpu.optim import HitRatio, NDCG, Optimizer, Trigger
    from bigdl_tpu.optim.methods import Adam

    if args.synthetic:
        n_users = max(args.synthetic // 8, 8)
        ratings = synthetic_ratings(n_users=n_users,
                                    n_items=max(n_users // 2, 30),
                                    per_user=8)
    else:
        ratings = read_data_sets(args.folder)

    neg_eval = args.neg_eval
    max_unseen = int(ratings[:, 1].max()) - 1
    if neg_eval > max_unseen:
        neg_eval = max_unseen  # tiny synthetic item sets
    pairs, labels, eval_rows = leave_one_out(
        ratings, args.neg_train, neg_eval)
    train = [Sample(pairs[i], labels[i]) for i in range(len(pairs))]
    test = [Sample(rows, 1.0) for rows in eval_rows]

    data = DataSet.array(train).transform(
        SampleToMiniBatch(args.batch_size))
    if args.cache_device:
        data = data.cache_on_device()
    model = NeuralCF(int(ratings[:, 0].max()), int(ratings[:, 1].max()),
                     embed_dim=args.embed_dim)
    opt = (Optimizer(model, data, nn.BCECriterion())
           .set_optim_method(Adam(args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_validation(Trigger.every_epoch(), test,
                           [HitRatio(args.topk, neg_eval),
                            NDCG(args.topk, neg_eval)],
                           batch_size=args.batch_size))
    apply_common(opt, args, train_summary, val_summary)
    opt.optimize()
    print(f"Final HitRatio@{args.topk}: {opt.state['score']:.4f}")
    return model


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
