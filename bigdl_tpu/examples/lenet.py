"""LeNet-5 / MNIST training main (reference models/lenet/Train.scala:35-105
and the scopt flags in models/lenet/Utils.scala).

    bigdl-tpu-lenet -f /data/mnist -b 128 -e 5 --checkpoint /tmp/ckpt
    bigdl-tpu-lenet --synthetic 2048 -e 2        # no dataset files needed
"""

from __future__ import annotations

from bigdl_tpu.examples.common import apply_common, base_parser, setup


def main(argv=None):
    args = base_parser("Train LeNet-5 on MNIST").parse_args(argv)
    train_summary, val_summary = setup(args, "lenet")

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.mnist import mnist_samples, synthetic_mnist
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import (
        Loss, Optimizer, SGD, Top1Accuracy, Trigger,
    )

    if args.synthetic:
        # hold out a split of ONE generation: synthetic_mnist's class
        # prototypes are seed-dependent, so a differently-seeded test
        # set would be a different task (validation stuck near chance)
        n_test = max(args.synthetic // 4, args.batch_size)
        samples = synthetic_mnist(args.synthetic + n_test, seed=0)
        train, test = samples[:args.synthetic], samples[args.synthetic:]
    else:
        train = mnist_samples(args.folder, train=True)
        test = mnist_samples(args.folder, train=False)

    data = DataSet.array(train).transform(SampleToMiniBatch(args.batch_size))
    if args.cache_device:
        data = data.cache_on_device()
    model = LeNet5(class_num=10)
    opt = (Optimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_validation(Trigger.every_epoch(), test,
                           [Top1Accuracy(), Loss(nn.ClassNLLCriterion())],
                           batch_size=args.batch_size))
    apply_common(opt, args, train_summary, val_summary)
    opt.optimize()
    print(f"Final validation score: {opt.state['score']:.4f}")
    return model


def cli():
    """Console entry: discard main()'s return value so the generated
    script exits 0 (sys.exit(<object>) would exit 1)."""
    main()


if __name__ == "__main__":
    main()
