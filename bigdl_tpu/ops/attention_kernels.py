"""Scaled dot-product attention kernels.

The reference computes attention as materialized [B, H, Tq, Tk] score
matrices through a graph of MM/SoftMax/Dropout layers
(reference: nn/Attention.scala — matmulLayer/softMaxLayer/dropLayer —
single-node, full materialization; SURVEY §5.7 notes the reference has no
flash/blockwise attention at all).

TPU-first redesign:

* :func:`flash_attention` — a Pallas TPU kernel implementing blockwise
  online-softmax attention (Flash-Attention-style): Q tiles stay resident
  in VMEM, K/V stream through in blocks, the softmax is computed with the
  running (max, sum) recurrence, so HBM traffic is O(T) not O(T²) and the
  QK^T / PV matmuls hit the MXU at [block_q, d] × [d, block_k] tile sizes.

* :func:`dot_product_attention` — the public entry: dispatches to the
  Pallas kernel on TPU (when shapes tile cleanly) and to a pure-XLA
  einsum implementation elsewhere; both paths are numerically equivalent
  (tested against each other and against torch SDPA).

Shapes follow [batch, heads, length, head_dim] ("BHTD").
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some backends
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["dot_product_attention", "flash_attention", "xla_attention"]

_NEG_INF = -1e9  # matches the reference's attention mask fill
                 # (nn/TransformerOperation.scala attentionBiasLowerTriangle)


# ---------------------------------------------------------------------------
# Pure-XLA reference path
# ---------------------------------------------------------------------------

def xla_attention(q, k, v, bias=None, *, causal: bool = False,
                  scale: Optional[float] = None):
    """Materialized attention: softmax(q k^T * scale + bias) v.

    q: [B, H, Tq, D]; k, v: [B, H, Tk, D]; bias broadcastable to
    [B, H, Tq, Tk].  Accumulation in fp32 regardless of input dtype.
    """
    *_, tq, d = q.shape
    tk = k.shape[-2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * jnp.float32(scale)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, _NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *,
                  block_k: int, causal: bool, scale: float, block_q: int):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    Refs are VMEM tiles: q_ref [block_q, d]; k_ref/v_ref [Tk, d] (whole
    K/V for this batch-head — fine for the Tk ≲ 4k tiles we target; the
    ring-attention layer shards longer sequences before this kernel);
    bias_ref [block_q, Tk] or None; o_ref [block_q, d].
    """
    q_idx = pl.program_id(1)
    tk = k_ref.shape[0]
    d = q_ref.shape[1]
    nblocks = tk // block_k

    q = q_ref[...].astype(jnp.float32) * scale

    def body(i, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        if bias_ref is not None:
            s = s + bias_ref[:, pl.dslice(i * block_k, block_k)].astype(
                jnp.float32)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # skip fully-masked K blocks beyond the diagonal
        nblocks_eff = jnp.minimum(
            nblocks, ((q_idx + 1) * block_q + block_k - 1) // block_k)
        acc, m, l = jax.lax.fori_loop(0, nblocks_eff, body, (acc0, m0, l0))
    else:
        acc, m, l = jax.lax.fori_loop(0, nblocks, body, (acc0, m0, l0))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, bias=None, *, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False):
    """Blockwise online-softmax attention as a Pallas TPU kernel.

    Requires Tq % block_q == 0 and Tk % block_k == 0 (the public
    :func:`dot_product_attention` pads/dispatches).  bias, if given, must
    broadcast to [B, H, Tq, Tk].
    """
    b, h, tq, d = q.shape
    tk = k.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    assert tq % block_q == 0 and tk % block_k == 0
    if causal and tq != tk:
        # the kernel's causal mask is start-aligned; xla_attention's is
        # end-aligned (tril k=tk-tq) — refuse the ambiguous case instead
        # of silently diverging
        raise ValueError("flash_attention causal requires tq == tk")

    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)

    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((None, tk, d), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((None, tk, d), lambda bh, i: (bh, 0, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        bias = jnp.broadcast_to(bias, (b, h, tq, tk)).reshape(b * h, tq, tk)
        in_specs.append(
            pl.BlockSpec((None, block_q, tk), lambda bh, i: (bh, i, 0)))
        args.append(bias)
        kern = functools.partial(_flash_kernel, block_k=block_k,
                                 causal=causal, scale=scale, block_q=block_q)
    else:
        def kern(q_ref, k_ref, v_ref, o_ref):
            _flash_kernel(q_ref, k_ref, v_ref, None, o_ref,
                          block_k=block_k, causal=causal, scale=scale,
                          block_q=block_q)

    out = pl.pallas_call(
        kern,
        grid=(b * h, tq // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, tq, d)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def dot_product_attention(q, k, v, bias=None, *, causal: bool = False,
                          scale: Optional[float] = None,
                          force: Optional[str] = None):
    """Public attention entry (used by nn.Attention and the transformer
    models).  Chooses the Pallas flash kernel on TPU when the sequence
    tiles cleanly, else the XLA path.  ``force`` ∈ {"flash", "xla", None};
    env var BIGDL_TPU_ATTENTION overrides the default choice.
    """
    choice = force or os.environ.get("BIGDL_TPU_ATTENTION")
    tq, tk, d = q.shape[-2], k.shape[-2], q.shape[-1]
    tiles = (tq % 128 == 0 and tk % 128 == 0 and d % 8 == 0
             and (not causal or tq == tk))
    if choice == "flash" or (choice is None and _on_tpu() and tiles):
        return flash_attention(q, k, v, bias, causal=causal, scale=scale,
                               interpret=not _on_tpu())
    return xla_attention(q, k, v, bias, causal=causal, scale=scale)
