"""Scaled dot-product attention kernels.

The reference computes attention as materialized [B, H, Tq, Tk] score
matrices through a graph of MM/SoftMax/Dropout layers
(reference: nn/Attention.scala — matmulLayer/softMaxLayer/dropLayer —
single-node, full materialization; SURVEY §5.7 notes the reference has no
flash/blockwise attention at all).

TPU-first redesign:

* :func:`flash_attention` — a Pallas TPU kernel implementing blockwise
  online-softmax attention (Flash-Attention-style).  K/V/bias are
  STREAMED block-by-block through the pallas grid (the kernel never
  holds a full [Tk, d] panel in VMEM — r03's ~4k ceiling is gone): the
  grid is (batch·heads, q-blocks, k-blocks) with the online-softmax
  (max, sum, acc) recurrence carried in VMEM scratch across the
  sequential k dimension, so HBM traffic is O(T) per query block and
  the QK^T / PV matmuls hit the MXU at [block_q, d] × [d, block_k]
  tile sizes while Pallas double-buffers the incoming K/V blocks.

  Training-ready: the function carries a ``jax.custom_vjp`` whose
  backward is itself blockwise Pallas — the forward additionally emits
  the per-row logsumexp, and the backward recomputes P tile-by-tile
  (dQ kernel streaming K/V; dK/dV kernel streaming Q/dO), never
  materializing the [Tq, Tk] score matrix.  The bias cotangent IS
  O(Tq·Tk); it is produced by a *separate* pallas_call so that when the
  bias is not differentiated (causal/padding masks — the common case)
  jit's dead-code elimination drops that kernel entirely.

* :func:`dot_product_attention` — the public entry: dispatches to the
  Pallas kernel on TPU (when shapes tile cleanly) and to a pure-XLA
  einsum implementation elsewhere; both paths are numerically equivalent
  (tested against each other and against torch SDPA, values and grads).

Shapes follow [batch, heads, length, head_dim] ("BHTD").
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.ops.pallas_compat import pltpu
from bigdl_tpu.ops.pallas_compat import compiler_params as _compiler_params

__all__ = ["dot_product_attention", "flash_attention",
           "flash_attention_partial", "xla_attention"]

_NEG_INF = -1e9  # matches the reference's attention mask fill
                 # (nn/TransformerOperation.scala attentionBiasLowerTriangle)


# ---------------------------------------------------------------------------
# Pure-XLA reference path
# ---------------------------------------------------------------------------

def xla_attention(q, k, v, bias=None, *, causal: bool = False,
                  scale: Optional[float] = None):
    """Materialized attention: softmax(q k^T * scale + bias) v.

    q: [B, H, Tq, D]; k, v: [B, H, Tk, D]; bias broadcastable to
    [B, H, Tq, Tk].  Accumulation in fp32 regardless of input dtype.
    """
    *_, tq, d = q.shape
    tk = k.shape[-2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * jnp.float32(scale)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, _NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash kernels — K/V streamed through the grid
# ---------------------------------------------------------------------------

def _auto_blocks(tq: int, tk: int, d: int, bias: bool = False):
    """Pick (block_q, block_k) for the flash kernels: the largest pair
    dividing the sequence lengths whose f32 score-shaped tiles fit the
    TPU scoped-VMEM budget.

    Block size is THE perf knob here.  At [128, 128] the grid for
    T=4096, B*H=64 is 65k programs of ~4 MFLOP each, so fixed
    per-program cost (DMA waits, grid bookkeeping) dominates the MXU
    work: measured 47x slower than [1024, 1024] on v5e.  Bigger tiles
    amortize that cost; the cap is the ~16 MiB scoped VMEM that must
    hold the f32 score-shaped intermediates (3 in the backward — p, dp,
    ds; with a bias, two more: the upcast bias tile and the dbias
    kernel's ds output) plus the streamed q/k/v/do tiles."""
    def divisors(t, choices):
        return [b for b in choices if t % b == 0]

    per_tile = 20 if bias else 12  # f32 score-shaped tiles, bytes/elem
    best = None
    for bq in divisors(tq, (1024, 768, 512, 384, 256, 128)) or [tq]:
        for bk in divisors(tk, (1024, 768, 512, 384, 256, 128)) or [tk]:
            vmem = per_tile * bq * bk + 6 * (bq + bk) * d
            if vmem > 14 * 2 ** 20:
                continue
            key = (bq * bk, bk)
            if best is None or key > best[0]:
                best = (key, bq, bk)
    if best is not None:
        return best[1], best[2]
    # nothing fits (odd lengths whose only listed divisor — the length
    # itself — blows the budget): fall back to the largest small
    # divisor, mirroring the ring's historic _pick_block tiling so a
    # forced kernel='flash' still runs instead of tripping the
    # divisibility assert
    fb = lambda t: next(b for b in (128, 64, 32, 16, 8, 4, 2, 1)
                        if t % b == 0)
    return fb(tq), fb(tk)


def _resolve_blocks(block_q, block_k, tq, tk, d, bias=False):
    """Fill None block sizes from :func:`_auto_blocks`; explicit sizes
    win.  Shared by every flash entry point so forward and backward
    kernels agree on the tiling."""
    if block_q is None or block_k is None:
        abq, abk = _auto_blocks(tq, tk, d, bias=bias)
        block_q = block_q or abq
        block_k = block_k or abk
    return int(block_q), int(block_k)


class _FlashCfg(NamedTuple):
    """Static kernel configuration (hashable: used as a custom_vjp
    nondiff argument)."""
    causal: bool
    scale: float
    block_q: int
    block_k: int
    interpret: bool


def _dimsem(*sems):
    """TPU compiler hint: which grid dims are parallel (megacore-
    splittable) vs sequential ("arbitrary" — carries a VMEM/output
    accumulator).  No-op where pltpu is unavailable."""
    if pltpu is None:  # pragma: no cover
        return {}
    return {"compiler_params": _compiler_params()(
        dimension_semantics=sems)}


def _scratch(shape):
    """VMEM scratch allocation (fp32 accumulator carried across the
    sequential k grid dimension)."""
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "flash_attention needs jax.experimental.pallas.tpu (VMEM "
            "scratch accumulators); use force='xla' / "
            "BIGDL_TPU_ATTENTION=xla on this backend")
    return pltpu.VMEM(shape, jnp.float32)


def _causal_mask(s, q_pos0, k_pos0, shape):
    q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = k_pos0 + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, cfg: _FlashCfg,
                      nk: int):
    """One (bh, q-block, k-block) program.  Refs are VMEM tiles: q_ref
    [block_q, d]; k_ref/v_ref [block_k, d] (ONE streamed block);
    bias_ref [block_q, block_k] or None; o_ref [block_q, d]; lse_ref
    [block_q, 1].  acc/m/l are VMEM scratch carrying the online-softmax
    state across the sequential k dimension."""
    block_q, block_k = cfg.block_q, cfg.block_k
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: blocks entirely above the diagonal contribute nothing
    needed = True
    if cfg.causal:
        needed = k_idx * block_k <= q_idx * block_q + block_q - 1

    @pl.when(needed)
    def _body():
        # dots run in the INPUT dtype (bf16 inputs drive the MXU at
        # native rate — upcasting to f32 first runs the MXU at a
        # fraction of peak) with f32 accumulation; the scale applies to
        # the f32 product, matching xla_attention's ordering
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * cfg.scale
        if bias_ref is not None:
            s = s + bias_ref[...].astype(jnp.float32)
        if cfg.causal:
            s = _causal_mask(s, q_idx * block_q, k_idx * block_k,
                             (block_q, block_k))
        m_prev = m_ref[...][:, 0]
        l_prev = l_ref[...][:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = (l_prev * alpha + jnp.sum(p, axis=-1))[:, None]
        m_ref[...] = m_new[:, None]
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(k_idx == nk - 1)
    def _finish():
        l = l_ref[...][:, 0]
        o_ref[...] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        lse_ref[...] = (m_ref[...][:, 0] + jnp.log(l))[:, None].astype(
            jnp.float32)


def _fwd_impl(q, k, v, bias, cfg: _FlashCfg):
    """Run the forward kernel; returns (out [B,H,Tq,D], lse [B*H,Tq,1])."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q, block_k = cfg.block_q, cfg.block_k
    nk = tk // block_k

    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)

    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, 0)),
        pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, j, 0)),
        pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, j, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        biasr = jnp.broadcast_to(bias, (b, h, tq, tk)).reshape(b * h, tq, tk)
        in_specs.append(pl.BlockSpec((None, block_q, block_k),
                                     lambda bh, i, j: (bh, i, j)))
        args.append(biasr)
        kern = functools.partial(_flash_fwd_kernel, cfg=cfg, nk=nk)
    else:
        def kern(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l):
            _flash_fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                              acc, m, l, cfg=cfg, nk=nk)

    out, lse = pl.pallas_call(
        kern,
        grid=(b * h, tq // block_q, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[_scratch((block_q, d)), _scratch((block_q, 1)),
                        _scratch((block_q, 1))],
        interpret=cfg.interpret,
        **_dimsem("parallel", "parallel", "arbitrary"),
    )(*args)
    return out.reshape(b, h, tq, d), lse


def _recompute_p(q, k_blk, bias_blk, lse, q_pos0, k_pos0, cfg,
                 shape):
    """Shared tile recompute for the backward kernels: the normalized
    softmax tile P = exp(s - lse) (masked entries → exp(-1e9-lse) = 0).
    q/k are the raw input-dtype tiles — the dot runs at MXU-native rate
    and the scale applies to the f32 product (same order as forward)."""
    s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * cfg.scale
    if bias_blk is not None:
        s = s + bias_blk
    if cfg.causal:
        s = _causal_mask(s, q_pos0, k_pos0, shape)
    return jnp.exp(s - lse)


def _dq_accum(acc_ref, q_ref, k_ref, v_ref, bias_blk, do_ref,
              lse_ref, delta_ref, q_pos0, k_pos0, cfg: _FlashCfg):
    """Shared dQ tile step: acc += [P ∘ (dO V^T − Δ)] K (P recomputed
    from the q/k tiles + lse).  Used by the full backward (positions
    from program_id) and the ring partial backward (positions scalar-
    prefetched)."""
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...].astype(jnp.float32)
    delta = delta_ref[...].astype(jnp.float32)
    k_blk = k_ref[...]
    v_blk = v_ref[...]
    p = _recompute_p(q, k_blk, bias_blk, lse, q_pos0, k_pos0, cfg,
                     (cfg.block_q, cfg.block_k))
    dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
        ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dkv_accum(dk_acc, dv_acc, k_ref, v_ref, q_ref, bias_blk, do_ref,
               lse_ref, delta_ref, q_pos0, k_pos0, cfg: _FlashCfg):
    """Shared dK/dV tile step: dV += P^T dO; dK += dS^T Q (the caller's
    finish step multiplies dK by `scale` once, so every dot here runs on
    raw input-dtype tiles at MXU-native rate)."""
    k = k_ref[...]
    v = v_ref[...]
    q_blk = q_ref[...]
    do_blk = do_ref[...]
    lse_blk = lse_ref[...].astype(jnp.float32)
    delta_blk = delta_ref[...].astype(jnp.float32)
    p = _recompute_p(q_blk, k, bias_blk, lse_blk, q_pos0, k_pos0, cfg,
                     (cfg.block_q, cfg.block_k))
    dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
        p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do_blk, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_blk)
    dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
        ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _flash_dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, acc_ref, *, cfg: _FlashCfg,
                     nk: int):
    """dQ for one (bh, q-block, k-block): K/V stream through the grid.
    dQ = scale * Σ_blocks [P ∘ (dO V^T − Δ)] K."""
    block_q, block_k = cfg.block_q, cfg.block_k
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    needed = True
    if cfg.causal:
        needed = k_idx * block_k <= q_idx * block_q + block_q - 1

    @pl.when(needed)
    def _body():
        bias_blk = None
        if bias_ref is not None:
            bias_blk = bias_ref[...].astype(jnp.float32)
        _dq_accum(acc_ref, q_ref, k_ref, v_ref, bias_blk, do_ref,
                  lse_ref, delta_ref, q_idx * block_q, k_idx * block_k,
                  cfg)

    @pl.when(k_idx == nk - 1)
    def _finish():
        dq_ref[...] = (acc_ref[...] * cfg.scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(k_ref, v_ref, q_ref, bias_ref, do_ref, lse_ref,
                      delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                      cfg: _FlashCfg, nq: int):
    """dK/dV for one (bh, k-block, q-block): Q/dO stream through the
    grid.  dV = P^T dO;  dK = scale * [P ∘ (dO V^T − Δ)]^T Q."""
    block_q, block_k = cfg.block_q, cfg.block_k
    k_idx = pl.program_id(1)
    q_idx = pl.program_id(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    needed = True
    if cfg.causal:
        # q blocks strictly before this k block are fully masked
        needed = q_idx * block_q + block_q - 1 >= k_idx * block_k

    @pl.when(needed)
    def _body():
        bias_blk = None
        if bias_ref is not None:
            bias_blk = bias_ref[...].astype(jnp.float32)
        _dkv_accum(dk_acc, dv_acc, k_ref, v_ref, q_ref, bias_blk,
                   do_ref, lse_ref, delta_ref, q_idx * block_q,
                   k_idx * block_k, cfg)

    @pl.when(q_idx == nq - 1)
    def _finish():
        dk_ref[...] = (dk_acc[...] * cfg.scale).astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_dbias_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                        delta_ref, ds_ref, *, cfg: _FlashCfg):
    """dBias tile [block_q, block_k] for one (bh, q-block, k-block):
    dS itself.  Materializes O(Tq·Tk) — only ever run when the bias is
    actually differentiated (a separate pallas_call so jit DCE removes
    it when the bias is a constant mask)."""
    block_q, block_k = cfg.block_q, cfg.block_k
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)

    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...].astype(jnp.float32)
    delta = delta_ref[...].astype(jnp.float32)
    k_blk = k_ref[...]
    v_blk = v_ref[...]
    p = _recompute_p(q, k_blk, bias_ref[...].astype(jnp.float32), lse,
                     q_idx * block_q, k_idx * block_k, cfg,
                     (block_q, block_k))
    dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds_ref[...] = (p * (dp - delta)).astype(ds_ref.dtype)


def _bwd_prep(q, k, bias, out, do):
    """Shared backward prologue: flattened (B*H) views, Δ, broadcast bias.
    Δ_i = Σ_d dO_id · O_id  (= Σ_j P_ij dP_ij), computed once in XLA."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    dor = do.reshape(b * h, tq, d)
    delta = jnp.sum(dor.astype(jnp.float32)
                    * out.reshape(b * h, tq, d).astype(jnp.float32),
                    axis=-1, keepdims=True)
    biasr = None
    if bias is not None:
        biasr = jnp.broadcast_to(bias, (b, h, tq, tk)).reshape(b * h, tq, tk)
    return dor, delta, biasr


def _bwd_impl(q, k, v, bias, out, lse, do, cfg: _FlashCfg, *,
              prep=None):
    """Blockwise backward: returns (dq, dk, dv)."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q, block_k = cfg.block_q, cfg.block_k
    nq, nk = tq // block_q, tk // block_k

    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    dor, delta, biasr = prep if prep is not None else _bwd_prep(
        q, k, bias, out, do)

    q_spec = pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, 0))
    kv_spec = pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, j, 0))
    row_spec = pl.BlockSpec((None, block_q, 1), lambda bh, i, j: (bh, i, 0))
    bias_spec = pl.BlockSpec((None, block_q, block_k),
                             lambda bh, i, j: (bh, i, j))

    # ---- dQ: grid (bh, q-block, k-block) ------------------------------
    dq_specs = [q_spec, kv_spec, kv_spec]
    dq_args = [qr, kr, vr]
    if biasr is not None:
        dq_specs.append(bias_spec)
        dq_args.append(biasr)
        dq_kern = functools.partial(_flash_dq_kernel, cfg=cfg, nk=nk)
    else:
        def dq_kern(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dq_ref, acc):
            _flash_dq_kernel(q_ref, k_ref, v_ref, None, do_ref, lse_ref,
                             delta_ref, dq_ref, acc, cfg=cfg, nk=nk)
    dq_args += [dor, lse, delta]
    dq_specs += [q_spec, row_spec, row_spec]
    dq = pl.pallas_call(
        dq_kern,
        grid=(b * h, nq, nk),
        in_specs=dq_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=cfg.interpret,
        **_dimsem("parallel", "parallel", "arbitrary"),
    )(*dq_args)

    # ---- dK/dV: grid (bh, k-block, q-block) ---------------------------
    kblk_spec = pl.BlockSpec((None, block_k, d), lambda bh, j, i: (bh, j, 0))
    qstream = pl.BlockSpec((None, block_q, d), lambda bh, j, i: (bh, i, 0))
    rowstream = pl.BlockSpec((None, block_q, 1),
                             lambda bh, j, i: (bh, i, 0))
    bias_stream = pl.BlockSpec((None, block_q, block_k),
                               lambda bh, j, i: (bh, i, j))
    dkv_specs = [kblk_spec, kblk_spec, qstream]
    dkv_args = [kr, vr, qr]
    if biasr is not None:
        dkv_specs.append(bias_stream)
        dkv_args.append(biasr)
        dkv_kern = functools.partial(_flash_dkv_kernel, cfg=cfg, nq=nq)
    else:
        def dkv_kern(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc):
            _flash_dkv_kernel(k_ref, v_ref, q_ref, None, do_ref, lse_ref,
                              delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                              cfg=cfg, nq=nq)
    dkv_args += [dor, lse, delta]
    dkv_specs += [qstream, rowstream, rowstream]
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(b * h, nk, nq),
        in_specs=dkv_specs,
        out_specs=[kblk_spec, kblk_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, tk, d), v.dtype)],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=cfg.interpret,
        **_dimsem("parallel", "parallel", "arbitrary"),
    )(*dkv_args)

    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


def _dbias_impl(q, k, v, bias, lse, cfg: _FlashCfg, *, prep):
    """Bias cotangent dS, reduced back to the (possibly broadcast) bias
    shape.  A standalone pallas_call: unused ⇒ DCE'd under jit."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q, block_k = cfg.block_q, cfg.block_k

    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    dor, delta, biasr = prep

    q_spec = pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, 0))
    kv_spec = pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, j, 0))
    row_spec = pl.BlockSpec((None, block_q, 1), lambda bh, i, j: (bh, i, 0))
    tile = pl.BlockSpec((None, block_q, block_k),
                        lambda bh, i, j: (bh, i, j))

    ds = pl.pallas_call(
        functools.partial(_flash_dbias_kernel, cfg=cfg),
        grid=(b * h, tq // block_q, tk // block_k),
        in_specs=[q_spec, kv_spec, kv_spec, tile, q_spec, row_spec,
                  row_spec],
        out_specs=tile,
        out_shape=jax.ShapeDtypeStruct((b * h, tq, tk), jnp.float32),
        interpret=cfg.interpret,
        **_dimsem("parallel", "parallel", "parallel"),
    )(qr, kr, vr, biasr, dor, lse, delta)

    ds = ds.reshape(b, h, tq, tk)
    # un-broadcast: right-align the bias shape against [B, H, Tq, Tk]
    # (numpy broadcasting aligns trailing dims), then sum over every dim
    # the original bias had as 1 (or lacked entirely)
    aligned = (1,) * (4 - bias.ndim) + tuple(bias.shape)
    for axis, (full, orig) in enumerate(zip((b, h, tq, tk), aligned)):
        if orig == 1 and full != 1:
            ds = jnp.sum(ds, axis=axis, keepdims=True)
    while ds.ndim > bias.ndim:
        ds = jnp.squeeze(ds, axis=0)
    return ds.astype(bias.dtype)


# ---------------------------------------------------------------------------
# Partial (carry-in/carry-out) flash step — the ring-attention kernel
# ---------------------------------------------------------------------------

def _flash_partial_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                          acc_in, m_in, l_in, acc_out, m_out, l_out, *,
                          cfg: _FlashCfg):
    """One (bh, q-block, k-block) program merging THIS K/V chunk into a
    running online-softmax state.  qoff/koff are scalar-prefetched
    GLOBAL positions of the chunks (traced values from the ring's
    axis_index arithmetic).  The output refs double as accumulators —
    their block index is constant over the inner k dimension, so they
    stay VMEM-resident across it."""
    block_q, block_k = cfg.block_q, cfg.block_k
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _seed():
        acc_out[...] = acc_in[...].astype(jnp.float32)
        m_out[...] = m_in[...].astype(jnp.float32)
        l_out[...] = l_in[...].astype(jnp.float32)

    q_pos0 = qoff_ref[0] + i * block_q
    k_pos0 = koff_ref[0] + j * block_k
    needed = True
    if cfg.causal:
        needed = k_pos0 <= q_pos0 + block_q - 1

    @pl.when(needed)
    def _body():
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * cfg.scale
        if cfg.causal:
            s = _causal_mask(s, q_pos0, k_pos0, (block_q, block_k))
        m_prev = m_out[...][:, 0]
        l_prev = l_out[...][:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_out[...] = (l_prev * alpha + jnp.sum(p, axis=-1))[:, None]
        m_out[...] = m_new[:, None]
        acc_out[...] = acc_out[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def flash_attention_partial(q, k, v, acc, m, l, *, q_offset, k_offset,
                            causal: bool = False,
                            scale: Optional[float] = None,
                            block_q: Optional[int] = None,
                            block_k: Optional[int] = None,
                            interpret: bool = False):
    """Merge blockwise attention of q [B,H,Tq,D] against ONE K/V chunk
    [B,H,Tk,D] into the running online-softmax state
    (acc [B,H,Tq,D] fp32, m/l [B,H,Tq] fp32); returns the updated
    state.  q_offset/k_offset are the chunks' global sequence positions
    (traced scalars fine — scalar-prefetched into the kernel), so the
    causal mask is exact across ring steps.  The caller finishes with
    ``out = acc / l[..., None]``.  Forward-only (the ring layer remats
    around it); no bias (the ring routes biased attention dense)."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q, block_k = _resolve_blocks(block_q, block_k, tq, tk, d)
    assert tq % block_q == 0 and tk % block_k == 0, (tq, tk)
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "flash_attention_partial needs jax.experimental.pallas.tpu "
            "(scalar prefetch); use kernel='xla' / BIGDL_TPU_ATTENTION="
            "xla on this backend")
    cfg = _FlashCfg(causal=bool(causal), scale=float(scale),
                    block_q=int(block_q), block_k=int(block_k),
                    interpret=bool(interpret))
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    accr = acc.reshape(b * h, tq, d).astype(jnp.float32)
    mr = m.reshape(b * h, tq, 1).astype(jnp.float32)
    lr = l.reshape(b * h, tq, 1).astype(jnp.float32)

    # with scalar prefetch, index maps receive the prefetch refs too
    q_spec = pl.BlockSpec((None, block_q, d),
                          lambda bh, i, j, *refs: (bh, i, 0))
    kv_spec = pl.BlockSpec((None, block_k, d),
                           lambda bh, i, j, *refs: (bh, j, 0))
    row_spec = pl.BlockSpec((None, block_q, 1),
                            lambda bh, i, j, *refs: (bh, i, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * h, tq // block_q, tk // block_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec, row_spec, row_spec],
    )
    acc2, m2, l2 = pl.pallas_call(
        functools.partial(_flash_partial_kernel, cfg=cfg),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((b * h, tq, d), jnp.float32),
                   jax.ShapeDtypeStruct((b * h, tq, 1), jnp.float32),
                   jax.ShapeDtypeStruct((b * h, tq, 1), jnp.float32)],
        interpret=cfg.interpret,
        **_dimsem("parallel", "parallel", "arbitrary"),
    )(jnp.asarray(q_offset, jnp.int32).reshape(1),
      jnp.asarray(k_offset, jnp.int32).reshape(1),
      qr, kr, vr, accr, mr, lr)
    return (acc2.reshape(b, h, tq, d), m2.reshape(b, h, tq),
            l2.reshape(b, h, tq))


def _flash_dq_partial_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                             do_ref, lse_ref, delta_ref, dq_ref,
                             acc_ref, *, cfg: _FlashCfg, nk: int):
    """dQ contribution of ONE visiting K/V chunk (ring backward).
    lse/delta are the FINAL whole-sequence values, so
    P = exp(s - lse) is already normalized; offsets are global."""
    block_q, block_k = cfg.block_q, cfg.block_k
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_pos0 = qoff_ref[0] + i * block_q
    k_pos0 = koff_ref[0] + j * block_k
    needed = True
    if cfg.causal:
        needed = k_pos0 <= q_pos0 + block_q - 1

    @pl.when(needed)
    def _body():
        _dq_accum(acc_ref, q_ref, k_ref, v_ref, None, do_ref, lse_ref,
                  delta_ref, q_pos0, k_pos0, cfg)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[...] = (acc_ref[...] * cfg.scale).astype(dq_ref.dtype)


def _flash_dkv_partial_kernel(qoff_ref, koff_ref, k_ref, v_ref, q_ref,
                              do_ref, lse_ref, delta_ref, dk_ref,
                              dv_ref, dk_acc, dv_acc, *,
                              cfg: _FlashCfg, nq: int):
    """dK/dV of ONE visiting chunk w.r.t. THIS device's Q/dO (ring
    backward); grid (bh, local k-blocks, local q-blocks)."""
    block_q, block_k = cfg.block_q, cfg.block_k
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_pos0 = qoff_ref[0] + i * block_q
    k_pos0 = koff_ref[0] + j * block_k
    needed = True
    if cfg.causal:
        needed = q_pos0 + block_q - 1 >= k_pos0

    @pl.when(needed)
    def _body():
        _dkv_accum(dk_acc, dv_acc, k_ref, v_ref, q_ref, None, do_ref,
                   lse_ref, delta_ref, q_pos0, k_pos0, cfg)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[...] = (dk_acc[...] * cfg.scale).astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _partial_rows(x, b, h, t):
    return x.reshape(b * h, t, 1).astype(jnp.float32)


def flash_attention_dq_partial(q, k, v, do, lse, delta, *, q_offset,
                               k_offset, causal, scale, block_q,
                               block_k, interpret):
    """dQ contribution of one visiting chunk (see ring backward).
    q/do [B,H,Tq,D]; k/v [B,H,Tk,D]; lse/delta [B,H,Tq] fp32 (FINAL
    whole-sequence logsumexp / Δ rows)."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q, block_k = _resolve_blocks(block_q, block_k, tq, tk, d)
    assert tq % block_q == 0 and tk % block_k == 0, (tq, tk)
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "the flash partial backward needs jax.experimental.pallas"
            ".tpu (scalar prefetch); use kernel='xla' / "
            "BIGDL_TPU_ATTENTION=xla on this backend")
    cfg = _FlashCfg(bool(causal), float(scale), int(block_q),
                    int(block_k), bool(interpret))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * h, tq // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i, j, *r: (bh, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, i, j, *r: (bh, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, i, j, *r: (bh, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda bh, i, j, *r: (bh, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda bh, i, j, *r: (bh, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda bh, i, j, *r: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, i, j, *r: (bh, i, 0)),
        scratch_shapes=[_scratch((block_q, d))],
    )
    dq = pl.pallas_call(
        functools.partial(_flash_dq_partial_kernel, cfg=cfg,
                          nk=tk // block_k),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), jnp.float32),
        interpret=cfg.interpret,
        **_dimsem("parallel", "parallel", "arbitrary"),
    )(jnp.asarray(q_offset, jnp.int32).reshape(1),
      jnp.asarray(k_offset, jnp.int32).reshape(1),
      q.reshape(b * h, tq, d), k.reshape(b * h, tk, d),
      v.reshape(b * h, tk, d), do.reshape(b * h, tq, d),
      _partial_rows(lse, b, h, tq), _partial_rows(delta, b, h, tq))
    return dq.reshape(b, h, tq, d)


def flash_attention_dkv_partial(q, k, v, do, lse, delta, *, q_offset,
                                k_offset, causal, scale, block_q,
                                block_k, interpret):
    """(dK, dV) of one visiting chunk against this device's Q/dO."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q, block_k = _resolve_blocks(block_q, block_k, tq, tk, d)
    assert tq % block_q == 0 and tk % block_k == 0, (tq, tk)
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "the flash partial backward needs jax.experimental.pallas"
            ".tpu (scalar prefetch); use kernel='xla' / "
            "BIGDL_TPU_ATTENTION=xla on this backend")
    cfg = _FlashCfg(bool(causal), float(scale), int(block_q),
                    int(block_k), bool(interpret))
    kblk = pl.BlockSpec((None, block_k, d), lambda bh, j, i, *r: (bh, j, 0))
    qstream = pl.BlockSpec((None, block_q, d),
                           lambda bh, j, i, *r: (bh, i, 0))
    rowstream = pl.BlockSpec((None, block_q, 1),
                             lambda bh, j, i, *r: (bh, i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * h, tk // block_k, tq // block_q),
        in_specs=[kblk, kblk, qstream, qstream, rowstream, rowstream],
        out_specs=[kblk, kblk],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_partial_kernel, cfg=cfg,
                          nq=tq // block_q),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((b * h, tk, d), jnp.float32),
                   jax.ShapeDtypeStruct((b * h, tk, d), jnp.float32)],
        interpret=cfg.interpret,
        **_dimsem("parallel", "parallel", "arbitrary"),
    )(jnp.asarray(q_offset, jnp.int32).reshape(1),
      jnp.asarray(k_offset, jnp.int32).reshape(1),
      k.reshape(b * h, tk, d), v.reshape(b * h, tk, d),
      q.reshape(b * h, tq, d), do.reshape(b * h, tq, d),
      _partial_rows(lse, b, h, tq), _partial_rows(delta, b, h, tq))
    return dk.reshape(b, h, tk, d), dv.reshape(b, h, tk, d)


# ---- custom_vjp wiring ----------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash3(q, k, v, cfg: _FlashCfg):
    out, _ = _fwd_impl(q, k, v, None, cfg)
    return out


def _flash3_fwd(q, k, v, cfg):
    out, lse = _fwd_impl(q, k, v, None, cfg)
    return out, (q, k, v, out, lse)


def _flash3_bwd(cfg, res, do):
    q, k, v, out, lse = res
    return _bwd_impl(q, k, v, None, out, lse, do, cfg)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash4(q, k, v, bias, cfg: _FlashCfg):
    out, _ = _fwd_impl(q, k, v, bias, cfg)
    return out


def _flash4_fwd(q, k, v, bias, cfg):
    out, lse = _fwd_impl(q, k, v, bias, cfg)
    return out, (q, k, v, bias, out, lse)


def _flash4_bwd(cfg, res, do):
    q, k, v, bias, out, lse = res
    prep = _bwd_prep(q, k, bias, out, do)
    dq, dk, dv = _bwd_impl(q, k, v, bias, out, lse, do, cfg, prep=prep)
    dbias = _dbias_impl(q, k, v, bias, lse, cfg, prep=prep)
    return dq, dk, dv, dbias


_flash4.defvjp(_flash4_fwd, _flash4_bwd)


def flash_attention(q, k, v, bias=None, *, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: bool = False):
    """Blockwise online-softmax attention as a Pallas TPU kernel, with a
    blockwise Pallas backward (``jax.custom_vjp``) so it is safe under
    ``jax.grad`` — the reference trains its Transformer/Attention stack
    (nn/Transformer.scala:749, nn/Attention.scala), so must we.

    block_q/block_k default to the largest tiling that fits VMEM (see
    :func:`_auto_blocks` — small blocks are grid-overhead-bound).
    Requires Tq % block_q == 0 and Tk % block_k == 0 (the public
    :func:`dot_product_attention` pads/dispatches).  bias, if given, must
    broadcast to [B, H, Tq, Tk].
    """
    b, h, tq, d = q.shape
    tk = k.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q, block_k = _resolve_blocks(block_q, block_k, tq, tk, d,
                                       bias=bias is not None)
    assert tq % block_q == 0 and tk % block_k == 0
    if causal and tq != tk:
        # the kernel's causal mask is start-aligned; xla_attention's is
        # end-aligned (tril k=tk-tq) — refuse the ambiguous case instead
        # of silently diverging
        raise ValueError("flash_attention causal requires tq == tk")
    cfg = _FlashCfg(causal=bool(causal), scale=float(scale),
                    block_q=int(block_q), block_k=int(block_k),
                    interpret=bool(interpret))
    if bias is None:
        return _flash3(q, k, v, cfg)
    return _flash4(q, k, v, bias, cfg)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def dot_product_attention(q, k, v, bias=None, *, causal: bool = False,
                          scale: Optional[float] = None,
                          force: Optional[str] = None):
    """Public attention entry (used by nn.Attention and the transformer
    models).  Chooses the Pallas flash kernel on TPU when the sequence
    tiles cleanly, else the XLA path.  ``force`` ∈ {"flash", "xla", None};
    env var BIGDL_TPU_ATTENTION overrides the default choice.
    """
    choice = force or os.environ.get("BIGDL_TPU_ATTENTION")
    tq, tk, d = q.shape[-2], k.shape[-2], q.shape[-1]
    tiles = (tq % 128 == 0 and tk % 128 == 0 and d % 8 == 0
             and (not causal or tq == tk))
    if choice == "flash" or (choice is None and _on_tpu() and tiles):
        return flash_attention(q, k, v, bias, causal=causal, scale=scale,
                               interpret=not _on_tpu())
    return xla_attention(q, k, v, bias, causal=causal, scale=scale)
