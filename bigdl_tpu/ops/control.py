"""Control-flow modules: data-dependent branching and loops inside jit.

Reference: nn/Scheduler.scala + nn/FrameManager.scala (DynamicGraph's
runtime interpreter for control-flow nodes) and nn/tf/ControlOps.scala
(Switch/Merge/Enter/Exit/NextIteration), nn/tf/DataFlowOps.scala
(TensorArray).  The reference needed a scheduler because the JVM had to
*interpret* control-flow ops per element; under XLA the compiler owns
control flow, so the TPU-native redesign is thin Module wrappers over
``lax.cond`` / ``lax.while_loop`` / ``lax.scan`` — same capability
(conditional branches, data-dependent loops, per-step accumulation),
compiled instead of interpreted, and differentiable where XLA supports
it (cond/scan; while_loop is forward-only).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.module import Module

__all__ = ["Cond", "WhileLoop", "Scan", "TensorArrayScan"]


class Cond(Module):
    """``forward((pred, x))`` → ``true_branch(x)`` when pred else
    ``false_branch(x)``; both branches are compiled, one executes
    (≙ the Switch/Merge pair of nn/tf/ControlOps.scala:1-326 — but as
    structured ``lax.cond`` instead of dataflow dead-tensor routing)."""

    def __init__(self, true_branch: Module, false_branch: Module):
        super().__init__()
        self.true_branch = true_branch
        self.false_branch = false_branch

    def forward(self, inputs):
        pred, x = inputs
        pred = jnp.asarray(pred)
        if pred.ndim:
            pred = pred.reshape(())
        return lax.cond(pred,
                        lambda v: self.true_branch(v),
                        lambda v: self.false_branch(v), x)


class WhileLoop(Module):
    """``forward(state)`` iterates ``body`` while ``cond_fn(state)``
    holds (≙ Enter/NextIteration/Exit frames of ControlOps + the
    FrameManager loop bookkeeping, as one ``lax.while_loop``).

    ``max_iterations`` adds the reference's loop guard: the condition
    becomes ``cond_fn(state) & (i < max_iterations)``."""

    def __init__(self, cond_fn: Callable, body: Module,
                 max_iterations: Optional[int] = None):
        super().__init__()
        self.cond_fn = cond_fn
        self.body = body
        self.max_iterations = max_iterations

    def forward(self, state):
        if self.max_iterations is None:
            return lax.while_loop(self.cond_fn,
                                  lambda s: self.body(s), state)
        limit = self.max_iterations

        def cond(carry):
            i, s = carry
            return jnp.logical_and(i < limit,
                                   jnp.asarray(self.cond_fn(s)))

        def body(carry):
            i, s = carry
            return i + 1, self.body(s)

        _, out = lax.while_loop(cond, body,
                                (jnp.zeros((), jnp.int32), state))
        return out


class Scan(Module):
    """Apply ``body`` over the time axis carrying state:
    ``forward((state0, xs))`` → ``(stateN, ys)`` where
    ``body((state, x_t))`` → ``(state', y_t)``.  The compiled analog of
    the Scheduler stepping a DynamicGraph per timestep."""

    def __init__(self, body: Module, time_axis: int = 1):
        super().__init__()
        self.body = body
        self.time_axis = time_axis

    def forward(self, inputs):
        state0, xs = inputs
        t_ax = self.time_axis
        xs_t = jnp.moveaxis(xs, t_ax, 0)

        def step(state, x_t):
            state2, y = self.body((state, x_t))
            return state2, y

        stateN, ys = lax.scan(step, state0, xs_t)
        return stateN, jnp.moveaxis(ys, 0, t_ax)


class TensorArrayScan(Module):
    """Per-step write-then-stack accumulation — the XLA-native shape of
    nn/tf/DataFlowOps.scala's TensorArray (write inside a loop, stack at
    exit).  ``forward(xs)`` applies ``body`` to each timestep and stacks
    the results; equivalent to TensorArray.scatter+stack semantics."""

    def __init__(self, body: Module, time_axis: int = 1):
        super().__init__()
        self.body = body
        self.time_axis = time_axis

    def forward(self, xs):
        t_ax = self.time_axis
        xs_t = jnp.moveaxis(xs, t_ax, 0)

        def step(_, x_t):
            return None, self.body(x_t)

        _, ys = lax.scan(step, None, xs_t)
        return jnp.moveaxis(ys, 0, t_ax)
