"""Hot-op kernels (Pallas TPU + XLA fallbacks).

This package is the TPU-native analogue of the reference's native kernel
layer (BigDL-core: MKL/MKL-DNN/BigQuant JNI — see SURVEY §2.9): the ops
where hand-scheduling beats the compiler live here, everything else is
left to XLA fusion.
"""

from bigdl_tpu.ops.attention_kernels import (
    dot_product_attention,
    flash_attention,
)
from bigdl_tpu.ops import operations  # noqa: F401
from bigdl_tpu.ops.operations import *  # noqa: F401,F403
from bigdl_tpu.ops.control import (  # noqa: F401
    Cond, Scan, TensorArrayScan, WhileLoop,
)
from bigdl_tpu.ops.feature_columns import (  # noqa: F401
    CategoricalColHashBucket, CategoricalColVocaList, CrossCol,
    IndicatorCol, Kv2Tensor, MkString,
)

__all__ = ["dot_product_attention", "flash_attention",
           "Cond", "WhileLoop", "Scan", "TensorArrayScan",
           "CategoricalColHashBucket", "CategoricalColVocaList",
           "CrossCol", "IndicatorCol", "MkString", "Kv2Tensor"] \
    + list(operations.__all__)
