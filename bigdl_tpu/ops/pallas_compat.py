"""Version-compat shims for Pallas-TPU, shared by every kernel module
(the same discipline as ``parallel.mesh.shard_map_compat``: one spelling
of each jax-version dance, re-imported by call sites)."""

from __future__ import annotations

try:  # TPU-specific bits; absent on some backends
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["pltpu", "compiler_params"]


def compiler_params():
    """Version-compat Pallas-TPU params class: jax renamed
    TPUCompilerParams -> CompilerParams; resolve whichever this jax
    ships, or None when pallas-tpu itself is absent — so call sites
    degrade with one ``is None`` check instead of re-guarding the
    import."""
    if pltpu is None:
        return None
    return getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
