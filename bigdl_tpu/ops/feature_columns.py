"""Feature-column preprocessing ops (wide-and-deep input path).

Reference: nn/ops/{CategoricalColHashBucket, CategoricalColVocaList,
CrossCol, IndicatorCol, MkString, Kv2Tensor, BucketizedCol}.scala — the
TF-feature-column analog ops BigDL runs host-side on String tensors.
These are HOST ops by design: string hashing/splitting cannot (and
should not) run on the accelerator; their dense outputs feed the
device.  Inputs are numpy object/str arrays of shape [batch] or
[batch, 1]; multi-value features are delimiter-joined strings.

Hashing uses the deterministic Java-style ``s[0]*31^(n-1) + …`` rolling
hash (Python's builtin ``hash`` is salted per process, which would make
feature crossing irreproducible across runs).

Ids are 1-BASED (1..n, 0 = padding), one above the reference's 0-based
ids: this framework's fixed-capacity SparseTensor and LookupTableSparse
treat 0 as the padding sentinel, so emitting 0-based ids would silently
drop every id-0 feature in the embedding path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from bigdl_tpu.core.module import Module
from bigdl_tpu.nn.sparse import SparseTensor

__all__ = [
    "CategoricalColHashBucket", "CategoricalColVocaList", "CrossCol",
    "IndicatorCol", "MkString", "Kv2Tensor", "java_string_hash",
]


def java_string_hash(s: str) -> int:
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & 0xFFFFFFFF
    # interpret as signed 32-bit like the JVM
    return h - (1 << 32) if h >= (1 << 31) else h


def _rows(x) -> List[str]:
    arr = np.asarray(x, dtype=object).reshape(-1)
    return ["" if v is None else str(v) for v in arr]


def _to_sparse(indices0, indices1, values, shape, dtype=np.int32):
    idx = np.stack([np.asarray(indices0, np.int32),
                    np.asarray(indices1, np.int32)], axis=1) \
        if len(indices0) else np.zeros((0, 2), np.int32)
    return SparseTensor(idx, np.asarray(values, dtype), shape)


def _categorical_forward(x, delimiter: str, value_fn, is_sparse: bool):
    """Shared split/collect scaffolding for the categorical ops:
    value_fn(feature_string) -> 1-based id."""
    rows = _rows(x)
    i0, i1, vals = [], [], []
    max_cols = 1
    for r, row in enumerate(rows):
        feats = [f for f in row.split(delimiter) if f != ""]
        max_cols = max(max_cols, len(feats))
        for c, f in enumerate(feats):
            i0.append(r)
            i1.append(c)
            vals.append(value_fn(f))
    shape = (len(rows), max_cols)
    if is_sparse:
        return _to_sparse(i0, i1, vals, shape)
    dense = np.zeros(shape, np.int32)  # 0 = padding/missing
    for r, c, v in zip(i0, i1, vals):
        dense[r, c] = v
    return dense


class CategoricalColHashBucket(Module):
    """String feature → hash-bucket ids
    (nn/ops/CategoricalColHashBucket.scala): ``id = hash(s) %
    hash_bucket_size``; multi-value features split on ``str_delimiter``;
    sparse output by default."""

    def __init__(self, hash_bucket_size: int, str_delimiter: str = ",",
                 is_sparse: bool = True):
        super().__init__()
        assert hash_bucket_size > 1
        self.hash_bucket_size = hash_bucket_size
        self.str_delimiter = str_delimiter
        self.is_sparse = is_sparse

    def _bucket(self, s: str) -> int:
        return java_string_hash(s) % self.hash_bucket_size + 1

    def forward(self, x):
        return _categorical_forward(x, self.str_delimiter, self._bucket,
                                    self.is_sparse)


class CategoricalColVocaList(Module):
    """String feature → vocabulary indices
    (nn/ops/CategoricalColVocaList.scala).  Unknown values map to
    ``len(vocab)`` when ``is_set_default`` else raise (strict)."""

    def __init__(self, vocab_list: Sequence[str], str_delimiter: str = ",",
                 is_set_default: bool = False, is_sparse: bool = True):
        super().__init__()
        self.vocab = {v: i for i, v in enumerate(vocab_list)}
        self.str_delimiter = str_delimiter
        self.is_set_default = is_set_default
        self.is_sparse = is_sparse

    def _index(self, f: str) -> int:
        if f not in self.vocab and not self.is_set_default:
            raise ValueError(
                f"value {f!r} not in the vocabulary (pass "
                f"is_set_default=True to map it to the default bucket)")
        return self.vocab.get(f, len(self.vocab)) + 1

    def forward(self, x):
        return _categorical_forward(x, self.str_delimiter, self._index,
                                    self.is_sparse)


class CrossCol(Module):
    """Cross N categorical columns into hashed ids
    (nn/ops/CrossCol.scala, ≙ tf.feature_column.crossed_column):
    the cartesian product of each row's feature sets, joined with '_',
    hashed into ``hash_bucket_size``."""

    def __init__(self, hash_bucket_size: int, str_delimiter: str = ","):
        super().__init__()
        self.hash_bucket_size = hash_bucket_size
        self.str_delimiter = str_delimiter

    def forward(self, columns):
        col_rows = [_rows(c) for c in columns]
        n = len(col_rows[0])
        assert all(len(c) == n for c in col_rows), "ragged batch"
        i0, i1, vals = [], [], []
        max_cols = 1
        for r in range(n):
            crossed = [""]
            for col in col_rows:
                feats = [f for f in col[r].split(self.str_delimiter)
                         if f != ""]
                crossed = [f"{a}_{f}" if a else f
                           for a in crossed for f in feats]
            max_cols = max(max_cols, len(crossed))
            for c, s in enumerate(crossed):
                i0.append(r)
                i1.append(c)
                vals.append(
                    java_string_hash(s) % self.hash_bucket_size + 1)
        return _to_sparse(i0, i1, vals, (n, max_cols))


class IndicatorCol(Module):
    """Sparse categorical ids → multi-hot dense (nn/ops/IndicatorCol.scala):
    output [batch, feat_len] with 1.0 at each id (counts when an id
    repeats)."""

    def __init__(self, feat_len: int, is_count: bool = True):
        super().__init__()
        self.feat_len = feat_len
        self.is_count = is_count

    def forward(self, sp: SparseTensor):
        idx = np.asarray(sp.indices)
        vals = np.asarray(sp.values).astype(np.int64)
        batch = int(sp.shape[0])
        out = np.zeros((batch, self.feat_len), np.float32)
        for (r, _c), v in zip(idx, vals):
            if v == 0:
                continue  # padding sentinel
            if 1 <= v <= self.feat_len:
                if self.is_count:
                    out[r, v - 1] += 1.0
                else:
                    out[r, v - 1] = 1.0
        return out


class MkString(Module):
    """Sparse rows → delimiter-joined strings (nn/ops/MkString.scala)."""

    def __init__(self, str_delimiter: str = ","):
        super().__init__()
        self.str_delimiter = str_delimiter

    def forward(self, sp: SparseTensor):
        idx = np.asarray(sp.indices)
        vals = np.asarray(sp.values)
        batch = int(sp.shape[0])
        rows: List[List[str]] = [[] for _ in range(batch)]
        for (r, _c), v in zip(idx, vals):
            if float(v) == 0.0:
                continue  # padding sentinel
            rows[r].append(str(int(v)) if float(v).is_integer()
                           else str(v))
        return np.asarray([self.str_delimiter.join(r) for r in rows],
                          dtype=object)


class Kv2Tensor(Module):
    """``"k:v,k:v"`` strings → dense [batch, feat_len]
    (nn/ops/Kv2Tensor.scala).  ``forward((kv_strings, feat_len))``."""

    def __init__(self, kv_delimiter: str = ",", item_delimiter: str = ":",
                 trans_type: int = 0):
        super().__init__()
        self.kv_delimiter = kv_delimiter
        self.item_delimiter = item_delimiter
        self.trans_type = trans_type

    def forward(self, inputs):
        kv, feat_len = inputs
        feat_len = int(feat_len)
        rows = _rows(kv)
        i0, i1, vals = [], [], []
        for r, row in enumerate(rows):
            for pair in row.split(self.kv_delimiter):
                if not pair:
                    continue
                k, _, v = pair.partition(self.item_delimiter)
                key = int(k)
                if not 0 <= key < feat_len:
                    raise ValueError(
                        f"Kv2Tensor: key {key} out of range "
                        f"[0, {feat_len}) in row {r} ({row!r})")
                i0.append(r)
                i1.append(key)
                vals.append(float(v))
        shape = (len(rows), feat_len)
        if self.trans_type == 1:
            return _to_sparse(i0, i1, vals, shape, np.float32)
        out = np.zeros(shape, np.float32)
        for r, c, v in zip(i0, i1, vals):
            out[r, c] += v  # duplicate keys sum, matching sparse mode
        return out
