"""Fused conv+BN+ReLU Pallas kernels for bottleneck convnets.

The reference's perf identity is fused conv/BN primitives inside its MKL
engine (reference: nn/mkldnn/SpatialConvolution.scala + nn/mkldnn/
SpatialBatchNormalization.scala fuse via mkl-dnn post-ops; whitepaper
docs/docs/whitepaper.md claims its throughput on exactly these chains).
On TPU the XLA path is HBM-bound on ResNet-style chains (measured:
docs/performance.md "Why ResNet-50 sits at ~39% MFU"): the conv kernels
already run at ~94% of HBM peak, so higher MFU needs structurally FEWER
BYTES, not better scheduling.

TPU-first redesign — a fused (normalize → relu → matmul → batch-stats)
op at the (BN_{i-1} → conv_i) granularity:

* forward: one Pallas kernel reads the PRE-normalization activation
  ``x`` tile-by-tile, applies the previous BN's per-channel
  ``(x - mean) * scale + beta`` and ReLU in VMEM (never materializing
  the normalized activation to HBM), feeds the MXU matmul for a 1x1
  conv, writes ``y``, and accumulates the NEXT BN's shifted one-pass
  statistics ``sum(y-K)``/``sum((y-K)^2)`` in VMEM across the
  sequential grid — the stats cost no extra HBM sweep.  HBM traffic is
  ``read A_in + write A_out``; the XLA chain pays two extra full
  activation passes (materializing the normalized input) plus an extra
  read when the stat reduce does not fuse.

* backward: ONE Pallas kernel per fused op.  The trick is the
  factoring: all C-sized algebra (folding batch stats into
  scale/shift, running-stat updates, the gradient flowing through the
  batch statistics) stays OUTSIDE the kernel in XLA, so the classic
  BatchNorm backward's two global reductions become (a) this kernel's
  VMEM-resident channel sums (``sum du``, ``sum du*x``) and (b) a
  gm/gs stats-cotangent fold-in that arrives as two [N] vectors.  The
  kernel reads ``x`` and ``dy`` once, recomputes the normalized
  activation and ``y`` in VMEM (FLOPs are free on an HBM-bound step),
  and writes ``dx`` — ``2*A_in + A_out`` of traffic where the XLA
  chain's bn-backward + wgrad + dgrad fusions pay ``~7*A_out +
  2*A_in`` around each 1x1.

Gradient correctness: the op's batch-stat outputs are real autodiff
outputs.  Downstream, XLA turns them into mean/var → scale/shift of the
next fused op; the cotangents (gm, gs) flow back INTO this op's
backward, where ``dy_total = dy + gm/M + 2*gs*(y-K)/M`` reconstructs
exactly the through-stats terms of the classic fused BN backward.  No
global reduction ever touches HBM twice.

Used by models/resnet.py's fused bottleneck path (BIGDL_TPU_FUSED_CONVBN).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.ops.pallas_compat import pltpu
from bigdl_tpu.ops.pallas_compat import compiler_params as _compiler_params

__all__ = ["fused_matmul_bn", "fused_matmul_bn_reference",
           "fused_block_supported", "fused_conv3x3_bn",
           "fused_conv3x3_bn_reference", "fused_conv3x3_supported",
           "shifted_batch_stats"]

_VMEM_BUDGET = 11 * 1024 * 1024  # leave headroom under the ~16MiB VMEM


class _Cfg(NamedTuple):
    """Static kernel config (hashable: custom_vjp nondiff arg)."""
    fuse_input: bool       # apply (x-mean)*scale+beta, relu before matmul
    emit_stats: bool       # accumulate shifted stats of y
    block_m: int
    interpret: bool


def _divisor_block(m: int, target: int, step: int = 8) -> Optional[int]:
    """Largest divisor of m that is a multiple of ``step`` and <= target."""
    best = None
    for bm in range(step, min(target, m) + 1, step):
        if m % bm == 0:
            best = bm
    return best


def _sublane(itemsize: int) -> int:
    """Mosaic's minimum second-to-minor tile dim per dtype: bf16 packs
    as (16, 128) tiles, f32 as (8, 128)."""
    return 16 if itemsize == 2 else 8


def _pick_block_m(m: int, k: int, n: int, itemsize: int) -> Optional[int]:
    """Block over M so that w + dW (resident) + the f32 working tiles fit
    VMEM.  The backward is the fattest occupant: w (bf16) + dW (f32)
    resident = 6*K*N bytes, plus ~(2 f32 + 1 input-width) copies of both
    the [BM,K] and [BM,N] tiles in flight.

    Blocks are rounded to the dtype's sublane multiple where a divisor
    exists (bf16 tiles are (16, 128): a block_m of 8 would lower via
    relayouts); when M has no aligned divisor we keep the old 8-step
    pick so the supported-problem set is unchanged."""
    resident = 6 * k * n
    if resident > _VMEM_BUDGET:
        return None
    per_row = (k + n) * (8 + itemsize) + k * 4
    avail = _VMEM_BUDGET - resident
    target = max(avail // max(per_row, 1), 8)
    cap = min(int(target), 1024)
    sub = _sublane(itemsize)
    if sub != 8:
        aligned = _divisor_block(m, cap, step=sub)
        if aligned is not None:
            return aligned
    return _divisor_block(m, cap)


def fused_block_supported(m: int, k: int, n: int,
                          itemsize: int = 2) -> bool:
    """Whether the Pallas path can tile this (M, K, N) problem."""
    return _pick_block_m(m, k, n, itemsize) is not None


# ---------------------------------------------------------------------------
# Pure-XLA reference (oracle for tests; fallback path)
# ---------------------------------------------------------------------------

def shifted_batch_stats(y, kshift):
    """One-pass shifted statistics over all but the channel axis, the
    exact algebra of nn/normalization.py BatchNormalization.forward:
    returns (sum(y-K), sum((y-K)^2)) in f32."""
    yf = y.astype(jnp.float32) - kshift.astype(jnp.float32)
    axes = tuple(range(y.ndim - 1))
    return jnp.sum(yf, axis=axes), jnp.sum(jnp.square(yf), axis=axes)


def fused_matmul_bn_reference(x2d, w2d, norm=None, kshift=None):
    """jnp mirror of the fused op (same rounding points: normalized
    input cast to x.dtype before the matmul, y cast to x.dtype before
    the statistics)."""
    if norm is not None:
        mean, scale, beta = norm
        xf = x2d.astype(jnp.float32)
        z = jax.nn.relu((xf - mean) * scale + beta).astype(x2d.dtype)
    else:
        z = x2d
    y = jnp.dot(z, w2d, preferred_element_type=jnp.float32).astype(x2d.dtype)
    if kshift is None:
        return y
    s1, s2 = shifted_batch_stats(y, kshift)
    return y, s1, s2


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, mean_ref, scale_ref, beta_ref, kshift_ref,
                y_ref, s1_ref, s2_ref, *, cfg: _Cfg):
    m = pl.program_id(0)
    if cfg.fuse_input:
        xf = x_ref[:].astype(jnp.float32)
        u = (xf - mean_ref[:]) * scale_ref[:] + beta_ref[:]
        z = jax.nn.relu(u).astype(x_ref.dtype)
    else:
        z = x_ref[:]
    y = jnp.dot(z, w_ref[:], preferred_element_type=jnp.float32)
    yc = y.astype(y_ref.dtype)
    y_ref[:] = yc
    if cfg.emit_stats:
        yf = yc.astype(jnp.float32) - kshift_ref[:]
        p1 = jnp.sum(yf, axis=0, keepdims=True)
        p2 = jnp.sum(yf * yf, axis=0, keepdims=True)

        @pl.when(m == 0)
        def _init():
            s1_ref[:] = p1
            s2_ref[:] = p2

        @pl.when(m != 0)
        def _acc():
            s1_ref[:] += p1
            s2_ref[:] += p2


def _bwd_kernel(x_ref, w_ref, mean_ref, scale_ref, beta_ref, kshift_ref,
                dy_ref, gm_ref, gs_ref,
                dx_ref, dw_ref, dsx_ref, dsu_ref, *, cfg: _Cfg):
    """One pass: recompute z (and y when the stats were differentiated),
    fold the stats cotangents into dy, then dW += z^T dy, dz = dy w^T,
    and the input-side BN backward's channel sums."""
    m = pl.program_id(0)
    xf = x_ref[:].astype(jnp.float32)
    if cfg.fuse_input:
        u = (xf - mean_ref[:]) * scale_ref[:] + beta_ref[:]
        z = jax.nn.relu(u).astype(x_ref.dtype)
    else:
        z = x_ref[:]
    dy = dy_ref[:].astype(jnp.float32)
    if cfg.emit_stats:
        # reconstruct y exactly as the forward produced it (rounded to
        # the output dtype) — the stats were taken on the rounded values
        y = jnp.dot(z, w_ref[:], preferred_element_type=jnp.float32)
        yr = y.astype(dy_ref.dtype).astype(jnp.float32)
        dy = dy + gm_ref[:] + gs_ref[:] * (yr - kshift_ref[:])
    dyl = dy.astype(dy_ref.dtype)
    dwp = jax.lax.dot_general(
        z, dyl, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(m == 0)
    def _init():
        dw_ref[:] = dwp

    @pl.when(m != 0)
    def _acc():
        dw_ref[:] += dwp

    dz = jax.lax.dot_general(
        dyl, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if cfg.fuse_input:
        du = jnp.where(u > 0, dz, 0.0)
        px = jnp.sum(du * xf, axis=0, keepdims=True)
        pu = jnp.sum(du, axis=0, keepdims=True)

        @pl.when(m == 0)
        def _inits():
            dsx_ref[:] = px
            dsu_ref[:] = pu

        @pl.when(m != 0)
        def _accs():
            dsx_ref[:] += px
            dsu_ref[:] += pu

        dx = du * scale_ref[:]
    else:
        dx = dz
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _row(v, n):
    """[1, n] f32 view of a vector (TPU VMEM wants >=2-D operands)."""
    if v is None:
        return jnp.zeros((1, n), jnp.float32)
    return jnp.asarray(v, jnp.float32).reshape(1, n)


def _vec_specs(k, n):
    zero = lambda m: (0, 0)
    return [
        pl.BlockSpec((1, k), zero),   # mean_in
        pl.BlockSpec((1, k), zero),   # scale_in
        pl.BlockSpec((1, k), zero),   # beta_in
        pl.BlockSpec((1, n), zero),   # kshift
    ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _fused_core(x, w, mean_in, scale_in, beta_in, kshift, cfg: _Cfg):
    out = _fused_fwd(x, w, mean_in, scale_in, beta_in, kshift, cfg)[0]
    return out


def _fused_fwd(x, w, mean_in, scale_in, beta_in, kshift, cfg: _Cfg):
    m, k = x.shape
    n = w.shape[1]
    bm = cfg.block_m
    outs = [jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32)]
    zero = lambda i: (0, 0)
    y, s1, s2 = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg=cfg),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                  pl.BlockSpec((k, n), zero)] + _vec_specs(k, n),
        out_specs=[pl.BlockSpec((bm, n), lambda i: (i, 0)),
                   pl.BlockSpec((1, n), zero),
                   pl.BlockSpec((1, n), zero)],
        out_shape=outs,
        compiler_params=_params(),
        interpret=cfg.interpret,
    )(x, w, mean_in, scale_in, beta_in, kshift)
    result = (y, s1[0], s2[0]) if cfg.emit_stats else y
    return result, (x, w, mean_in, scale_in, beta_in, kshift)


def _params():
    if pltpu is None:
        return None
    return _compiler_params()(dimension_semantics=("arbitrary",))


def _fused_bwd(cfg: _Cfg, res, ct):
    x, w, mean_in, scale_in, beta_in, kshift = res
    m, k = x.shape
    n = w.shape[1]
    bm = cfg.block_m
    if cfg.emit_stats:
        dy, gm, gs = ct
        # s1 = sum(y-K), s2 = sum((y-K)^2) are SUMS, so
        # dy_total = dy + gm + 2*gs * (y - K); fold the factor of 2 in
        # here so the kernel does one fma per element
        gm_row = gm.reshape(1, n).astype(jnp.float32)
        gs_row = (2.0 * gs).reshape(1, n).astype(jnp.float32)
    else:
        dy = ct
        gm_row = jnp.zeros((1, n), jnp.float32)
        gs_row = gm_row
    zero = lambda i: (0, 0)
    outs = [jax.ShapeDtypeStruct((m, k), x.dtype),
            jax.ShapeDtypeStruct((k, n), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32)]
    dx, dw, dsx, dsu = pl.pallas_call(
        functools.partial(_bwd_kernel, cfg=cfg),
        grid=(m // bm,),
        in_specs=([pl.BlockSpec((bm, k), lambda i: (i, 0)),
                   pl.BlockSpec((k, n), zero)] + _vec_specs(k, n)
                  + [pl.BlockSpec((bm, n), lambda i: (i, 0)),
                     pl.BlockSpec((1, n), zero),
                     pl.BlockSpec((1, n), zero)]),
        out_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                   pl.BlockSpec((k, n), zero),
                   pl.BlockSpec((1, k), zero),
                   pl.BlockSpec((1, k), zero)],
        out_shape=outs,
        compiler_params=_params(),
        interpret=cfg.interpret,
    )(x, w, mean_in, scale_in, beta_in, kshift, dy, gm_row, gs_row)
    dw = dw.astype(w.dtype)
    if cfg.fuse_input:
        # channel-vector cotangents from the kernel's sums:
        #   u = (x - mean) * scale + beta
        #   dscale = sum du*(x-mean);  dbeta = sum du;  dmean = -scale*dbeta
        dsu_v = dsu[0]
        dscale = dsx[0] - jnp.asarray(mean_in, jnp.float32)[0] * dsu_v
        dmean = -jnp.asarray(scale_in, jnp.float32)[0] * dsu_v
        dbeta = dsu_v
        return (dx, dw, dmean.reshape(1, k), dscale.reshape(1, k),
                dbeta.reshape(1, k), jnp.zeros_like(kshift))
    zk = jnp.zeros((1, k), jnp.float32)
    return dx, dw, zk, zk, zk, jnp.zeros_like(kshift)


_fused_core.defvjp(_fused_fwd, _fused_bwd)


def fused_matmul_bn(x2d, w2d, *, norm=None, kshift=None,
                    block_m: Optional[int] = None,
                    interpret: bool = False):
    """Fused (normalize → relu → matmul → batch-stats) for 1x1 convs.

    x2d: [M, K] pre-normalization activation (NHWC collapsed to rows);
    w2d: [K, N] (HWIO 1x1 kernel sliced to [Cin, Cout]);
    norm: optional (mean, scale, beta) f32 [K] vectors — the PREVIOUS
      BN folded to subtract-first form (scale = gamma * rsqrt(var+eps));
      None = feed x through unchanged (first conv of a chain);
    kshift: optional f32 [N] shift (the next BN's running_mean, as in
      BatchNormalization.forward's one-pass trick); None = no stats.
      Treated as a CONSTANT under autodiff (zero cotangent) — callers
      must pass it through jax.lax.stop_gradient, exactly as
      BatchNormalization.batch_stats does with its running_mean.

    Returns y [M, N] (and (sum(y-K), sum((y-K)^2)) f32 [N] when kshift
    is given).  Differentiable: jax.custom_vjp with a single fused
    Pallas backward pass.
    """
    m, k = x2d.shape
    kk, n = w2d.shape
    assert k == kk, (x2d.shape, w2d.shape)
    if block_m is None:
        block_m = _pick_block_m(m, k, n, x2d.dtype.itemsize)
    if block_m is None or m % block_m:
        raise ValueError(
            f"fused_matmul_bn cannot tile M={m} K={k} N={n}; "
            "use fused_block_supported() to pre-check")
    cfg = _Cfg(fuse_input=norm is not None, emit_stats=kshift is not None,
               block_m=int(block_m), interpret=bool(interpret))
    if norm is not None:
        mean_in, scale_in, beta_in = (_row(v, k) for v in norm)
    else:
        mean_in = scale_in = beta_in = _row(None, k)
    ks = _row(kshift, n) if kshift is not None else _row(None, n)
    return _fused_core(x2d, w2d, mean_in, scale_in, beta_in, ks, cfg)


# ---------------------------------------------------------------------------
# 3x3 stride-1 SAME conv with fused input normalize+relu and stats
# epilogue — the bottleneck's conv2 (conv-as-9-shifted-matmuls; the MXU
# sees [BH*W, C] x [C, Co] tiles, HBM sees each activation row once).
# Halo rows ride as two extra 1-row block refs (pallas blocks cannot
# overlap); image-boundary rows are zero-masked in VMEM, which IS the
# SAME zero padding.
# ---------------------------------------------------------------------------

class _Conv3Cfg(NamedTuple):
    fuse_input: bool
    emit_stats: bool
    block_h: int
    interpret: bool


def _pick_block_h(h: int, w: int, c: int, co: int,
                  itemsize: int) -> Optional[int]:
    """Block over H.  Resident: w9 (input width) + dW9 (f32) =
    9*C*Co*(itemsize+4); per row-of-block: the haloed x/z/dy tiles (at
    the input width) plus the f32 working copies."""
    resident = 9 * c * co * (itemsize + 4)
    if resident > _VMEM_BUDGET:
        return None
    per_row = w * (c * (2 * itemsize + 8) + co * (itemsize + 8))
    avail = _VMEM_BUDGET - resident
    target = (avail // max(per_row, 1)) - 2
    if target < 1:
        return None  # even a 1-row block would blow the VMEM budget
    cap = min(int(target), h)
    # prefer block_h with block_h*W a multiple of the dtype sublane
    # count (the tiles flatten to (block_h*W, C) rows): smallest step
    # that makes the product aligned is sublane/gcd(sublane, W).  Fall
    # back to any divisor so the supported set is unchanged.
    sub = _sublane(itemsize)
    step = sub // math.gcd(sub, w)
    if step > 1:
        aligned = _divisor_block(h, cap, step=step)
        if aligned is not None:
            return aligned
    return _divisor_block(h, cap, step=1)


def fused_conv3x3_supported(h: int, w: int, c: int, co: int,
                            itemsize: int = 2) -> bool:
    return _pick_block_h(h, w, c, co, itemsize) is not None


def _nz_rows(x, mean, scale, beta, fuse_input, out_dtype):
    """normalize+relu rows in f32 registers, rounded to the compute
    dtype (the same rounding point as the unfused path's materialized
    activation)."""
    if not fuse_input:
        return x
    u = (x.astype(jnp.float32) - mean) * scale + beta
    return jax.nn.relu(u).astype(out_dtype)


def _wshift(rows, dw):
    """SAME-padding column shift: output col w consumes input col
    w + dw - 1."""
    if dw == 0:
        pad = jnp.zeros_like(rows[:, :1])
        return jnp.concatenate([pad, rows[:, :-1]], axis=1)
    if dw == 2:
        pad = jnp.zeros_like(rows[:, :1])
        return jnp.concatenate([rows[:, 1:], pad], axis=1)
    return rows


def _conv3_fwd_kernel(xt_ref, xm_ref, xb_ref, w_ref, mean_ref,
                      scale_ref, beta_ref, kshift_ref,
                      y_ref, s1_ref, s2_ref, *, cfg: _Conv3Cfg):
    i = pl.program_id(1)
    ni = pl.num_programs(1)
    first = (pl.program_id(0) == 0) & (i == 0)
    bh = cfg.block_h
    dt = xm_ref.dtype

    xm = xm_ref[0]                       # [BH, W, C]
    xt = xt_ref[0, 0][None]              # [1, W, C]
    xb = xb_ref[0, 0][None]
    # boundary rows are zero AFTER normalize+relu (SAME zero padding of
    # the conv INPUT z, which is the normalized activation)
    zt = _nz_rows(xt, mean_ref[:], scale_ref[:], beta_ref[:],
                  cfg.fuse_input, dt) * jnp.where(i > 0, 1, 0).astype(dt)
    zb = _nz_rows(xb, mean_ref[:], scale_ref[:], beta_ref[:],
                  cfg.fuse_input, dt) * jnp.where(i < ni - 1, 1,
                                                  0).astype(dt)
    zm = _nz_rows(xm, mean_ref[:], scale_ref[:], beta_ref[:],
                  cfg.fuse_input, dt)
    z = jnp.concatenate([zt, zm, zb], axis=0)   # [BH+2, W, C]

    w_, c = z.shape[1], z.shape[2]
    co = w_ref.shape[-1]
    acc = jnp.zeros((bh * w_, co), jnp.float32)
    for dh in range(3):
        rows = z[dh:dh + bh]
        for dw in range(3):
            patch = _wshift(rows, dw).reshape(bh * w_, c)
            acc += jnp.dot(patch, w_ref[dh, dw],
                           preferred_element_type=jnp.float32)
    yc = acc.astype(dt).reshape(bh, w_, co)
    y_ref[0] = yc
    if cfg.emit_stats:
        yf = yc.astype(jnp.float32) - kshift_ref[0][None]
        p1 = jnp.sum(yf, axis=(0, 1), keepdims=False)[None]
        p2 = jnp.sum(yf * yf, axis=(0, 1), keepdims=False)[None]

        @pl.when(first)
        def _init():
            s1_ref[:] = p1
            s2_ref[:] = p2

        @pl.when(~first)
        def _acc():
            s1_ref[:] += p1
            s2_ref[:] += p2


def _conv3_bwd_kernel(xt_ref, xm_ref, xb_ref, w_ref, mean_ref,
                      scale_ref, beta_ref, kshift_ref,
                      yt_ref, ym_ref, yb_ref,
                      dyt_ref, dym_ref, dyb_ref, gm_ref, gs_ref,
                      dx_ref, dw_ref, dsx_ref, dsu_ref,
                      *, cfg: _Conv3Cfg):
    """One pass per block: recompute z (haloed), fold the stats
    cotangents into dy using the SAVED forward output y (haloed — so
    halo rows fold exactly without a 2-deep recompute), accumulate the
    9 dW tiles and the BN-chain channel sums, and produce dx for the
    block's main rows (complete thanks to the dy halo)."""
    i = pl.program_id(1)
    ni = pl.num_programs(1)
    first = (pl.program_id(0) == 0) & (i == 0)
    bh = cfg.block_h
    dt = xm_ref.dtype

    mean, scale, beta = mean_ref[:], scale_ref[:], beta_ref[:]
    xm = xm_ref[0]
    top_on = jnp.where(i > 0, 1, 0).astype(dt)
    bot_on = jnp.where(i < ni - 1, 1, 0).astype(dt)
    zt = _nz_rows(xt_ref[0, 0][None], mean, scale, beta,
                  cfg.fuse_input, dt) * top_on
    zb = _nz_rows(xb_ref[0, 0][None], mean, scale, beta,
                  cfg.fuse_input, dt) * bot_on
    zm = _nz_rows(xm, mean, scale, beta, cfg.fuse_input, dt)
    z = jnp.concatenate([zt, zm, zb], axis=0)      # [BH+2, W, C]

    w_, c = z.shape[1], z.shape[2]
    co = dym_ref.shape[-1]

    def fold(dy_raw, y_raw):
        dy = dy_raw.astype(jnp.float32)
        if cfg.emit_stats:
            yf = y_raw.astype(jnp.float32)
            dy = dy + gm_ref[0][None] + gs_ref[0][None] * (
                yf - kshift_ref[0][None])
        return dy

    dym = fold(dym_ref[0], ym_ref[0])              # [BH, W, Co] f32
    dyt = fold(dyt_ref[0, 0][None], yt_ref[0, 0][None]) \
        * top_on.astype(jnp.float32)
    dyb = fold(dyb_ref[0, 0][None], yb_ref[0, 0][None]) \
        * bot_on.astype(jnp.float32)
    dym_l = dym.astype(dt)
    dy3 = jnp.concatenate([dyt.astype(dt), dym_l, dyb.astype(dt)],
                          axis=0)                  # [BH+2, W, Co]

    # dW[dh,dw] += z_patch^T dy_main
    for dh in range(3):
        rows = z[dh:dh + bh]
        for dw in range(3):
            patch = _wshift(rows, dw).reshape(bh * w_, c)
            dwp = jax.lax.dot_general(
                patch, dym_l.reshape(bh * w_, co),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

            @pl.when(first)
            def _init(dh=dh, dw=dw, dwp=dwp):
                dw_ref[dh, dw] = dwp

            @pl.when(~first)
            def _acc(dh=dh, dw=dw, dwp=dwp):
                dw_ref[dh, dw] += dwp

    # dgrad (transposed conv): dz[r,w] = sum_{dh,dw} dy[r+1-(2-dh),
    # w+1-(2-dw)] @ w[dh,dw]^T — expressed as the same 9-shift pattern
    # on the haloed dy with flipped taps and swapped channels
    dz = jnp.zeros((bh * w_, c), jnp.float32)
    for dh in range(3):
        rows = dy3[dh:dh + bh]
        for dw in range(3):
            patch = _wshift(rows, dw).reshape(bh * w_, co)
            dz += jax.lax.dot_general(
                patch, w_ref[2 - dh, 2 - dw],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    dz = dz.reshape(bh, w_, c)

    if cfg.fuse_input:
        u = (xm.astype(jnp.float32) - mean) * scale + beta
        du = jnp.where(u > 0, dz, 0.0)
        px = jnp.sum(du * xm.astype(jnp.float32), axis=(0, 1))[None]
        pu = jnp.sum(du, axis=(0, 1))[None]

        @pl.when(first)
        def _inits():
            dsx_ref[:] = px
            dsu_ref[:] = pu

        @pl.when(~first)
        def _accs():
            dsx_ref[:] += px
            dsu_ref[:] += pu

        dx = du * scale
    else:
        dx = dz
    dx_ref[0] = dx.astype(dx_ref.dtype)


def fused_conv3x3_bn_reference(x4d, w, norm=None, kshift=None):
    """jnp mirror (same rounding points) of the fused 3x3 op."""
    if norm is not None:
        mean, scale, beta = norm
        xf = x4d.astype(jnp.float32)
        z = jax.nn.relu((xf - mean) * scale + beta).astype(x4d.dtype)
    else:
        z = x4d
    y = jax.lax.conv_general_dilated(
        z, w, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(x4d.dtype)
    if kshift is None:
        return y
    s1, s2 = shifted_batch_stats(y, kshift)
    return y, s1, s2


def _conv3_specs(b, h, w_, c, co, bh):
    main = pl.BlockSpec((1, bh, w_, c), lambda b_, i: (b_, i, 0, 0))
    top = pl.BlockSpec(
        (1, 1, w_, c),
        lambda b_, i: (b_, jnp.maximum(i * bh - 1, 0), 0, 0))
    bot = pl.BlockSpec(
        (1, 1, w_, c),
        lambda b_, i: (b_, jnp.minimum((i + 1) * bh, h - 1), 0, 0))
    vec_c = pl.BlockSpec((1, c), lambda b_, i: (0, 0))
    vec_co = pl.BlockSpec((1, co), lambda b_, i: (0, 0))
    wspec = pl.BlockSpec((3, 3, c, co), lambda b_, i: (0, 0, 0, 0))
    return main, top, bot, vec_c, vec_co, wspec


def _conv3_params():
    if pltpu is None:
        return None
    return _compiler_params()(
        dimension_semantics=("arbitrary", "arbitrary"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _conv3_core(x, w, mean_in, scale_in, beta_in, kshift,
                cfg: _Conv3Cfg):
    return _conv3_fwd(x, w, mean_in, scale_in, beta_in, kshift, cfg)[0]


def _conv3_fwd(x, w, mean_in, scale_in, beta_in, kshift, cfg: _Conv3Cfg):
    b, h, w_, c = x.shape
    co = w.shape[-1]
    bh = cfg.block_h
    main, top, bot, vec_c, vec_co, wspec = _conv3_specs(
        b, h, w_, c, co, bh)
    ymain = pl.BlockSpec((1, bh, w_, co), lambda b_, i: (b_, i, 0, 0))
    scal = pl.BlockSpec((1, co), lambda b_, i: (0, 0))
    outs = [jax.ShapeDtypeStruct((b, h, w_, co), x.dtype),
            jax.ShapeDtypeStruct((1, co), jnp.float32),
            jax.ShapeDtypeStruct((1, co), jnp.float32)]
    y, s1, s2 = pl.pallas_call(
        functools.partial(_conv3_fwd_kernel, cfg=cfg),
        grid=(b, h // bh),
        in_specs=[top, main, bot, wspec, vec_c, vec_c, vec_c, vec_co],
        out_specs=[ymain, scal, scal],
        out_shape=outs,
        compiler_params=_conv3_params(),
        interpret=cfg.interpret,
    )(x, x, x, w, mean_in, scale_in, beta_in, kshift)
    result = (y, s1[0], s2[0]) if cfg.emit_stats else y
    return result, (x, w, mean_in, scale_in, beta_in, kshift, y)


def _conv3_bwd(cfg: _Conv3Cfg, res, ct):
    x, w, mean_in, scale_in, beta_in, kshift, y = res
    b, h, w_, c = x.shape
    co = w.shape[-1]
    bh = cfg.block_h
    if cfg.emit_stats:
        dy, gm, gs = ct
        gm_row = gm.reshape(1, co).astype(jnp.float32)
        gs_row = (2.0 * gs).reshape(1, co).astype(jnp.float32)
    else:
        dy = ct
        gm_row = jnp.zeros((1, co), jnp.float32)
        gs_row = gm_row
    main, top, bot, vec_c, vec_co, wspec = _conv3_specs(
        b, h, w_, c, co, bh)
    ymain = pl.BlockSpec((1, bh, w_, co), lambda b_, i: (b_, i, 0, 0))
    ytop = pl.BlockSpec(
        (1, 1, w_, co),
        lambda b_, i: (b_, jnp.maximum(i * bh - 1, 0), 0, 0))
    ybot = pl.BlockSpec(
        (1, 1, w_, co),
        lambda b_, i: (b_, jnp.minimum((i + 1) * bh, h - 1), 0, 0))
    dwspec = pl.BlockSpec((3, 3, c, co), lambda b_, i: (0, 0, 0, 0))
    outs = [jax.ShapeDtypeStruct((b, h, w_, c), x.dtype),
            jax.ShapeDtypeStruct((3, 3, c, co), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32)]
    dx, dw, dsx, dsu = pl.pallas_call(
        functools.partial(_conv3_bwd_kernel, cfg=cfg),
        grid=(b, h // bh),
        in_specs=[top, main, bot, wspec, vec_c, vec_c, vec_c, vec_co,
                  ytop, ymain, ybot, ytop, ymain, ybot,
                  vec_co, vec_co],
        out_specs=[main, dwspec,
                   pl.BlockSpec((1, c), lambda b_, i: (0, 0)),
                   pl.BlockSpec((1, c), lambda b_, i: (0, 0))],
        out_shape=outs,
        compiler_params=_conv3_params(),
        interpret=cfg.interpret,
    )(x, x, x, w, mean_in, scale_in, beta_in, kshift,
      y, y, y, dy, dy, dy, gm_row, gs_row)
    dw = dw.astype(w.dtype)
    if cfg.fuse_input:
        dsu_v = dsu[0]
        dscale = dsx[0] - jnp.asarray(mean_in, jnp.float32)[0] * dsu_v
        dmean = -jnp.asarray(scale_in, jnp.float32)[0] * dsu_v
        return (dx, dw, dmean.reshape(1, c), dscale.reshape(1, c),
                dsu_v.reshape(1, c), jnp.zeros_like(kshift))
    zk = jnp.zeros((1, c), jnp.float32)
    return dx, dw, zk, zk, zk, jnp.zeros_like(kshift)


_conv3_core.defvjp(_conv3_fwd, _conv3_bwd)


def fused_conv3x3_bn(x4d, w, *, norm=None, kshift=None,
                     block_h: Optional[int] = None,
                     interpret: bool = False):
    """Fused (normalize → relu → 3x3 stride-1 SAME conv → batch-stats)
    for NHWC inputs — the bottleneck's conv2.

    x4d: [B, H, W, C]; w: [3, 3, C, Co] (HWIO);
    norm: optional (mean, scale, beta) f32 [C] (the previous BN folded
      to subtract-first form); kshift: optional f32 [Co] (next BN's
      running_mean, stop-gradient — see fused_matmul_bn).

    Returns y [B, H, W, Co] (+ (sum(y-K), sum((y-K)^2)) when kshift
    given).  jax.custom_vjp: single fused Pallas backward per block
    (dgrad + the 9 wgrad tiles + BN-chain channel sums), halo rows via
    1-row block refs, stats fold on halo rows taken from the SAVED
    forward output so no 2-deep halo is needed.
    """
    b, h, w_, c = x4d.shape
    assert w.shape[:3] == (3, 3, c), (w.shape, x4d.shape)
    co = w.shape[-1]
    if block_h is None:
        block_h = _pick_block_h(h, w_, c, co, x4d.dtype.itemsize)
    if block_h is None or h % block_h:
        raise ValueError(
            f"fused_conv3x3_bn cannot tile H={h} W={w_} C={c} Co={co}; "
            "use fused_conv3x3_supported() to pre-check")
    cfg = _Conv3Cfg(fuse_input=norm is not None,
                    emit_stats=kshift is not None,
                    block_h=int(block_h), interpret=bool(interpret))
    if norm is not None:
        mean_in, scale_in, beta_in = (_row(v, c) for v in norm)
    else:
        mean_in = scale_in = beta_in = _row(None, c)
    ks = _row(kshift, co) if kshift is not None else _row(None, co)
    return _conv3_core(x4d, w, mean_in, scale_in, beta_in, ks, cfg)
