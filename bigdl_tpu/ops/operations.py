"""TensorFlow-style stateless operations.

Reference: nn/ops/ (71 files — Operation = AbstractModule with no
backward, used for TF graph execution) and nn/ops/Operation.scala.
Each op is a thin Module over the matching jax/jnp primitive; under jit
they fuse into the surrounding computation, so there is no per-op
dispatch cost as in the reference's per-layer JNI calls.

Shape-like operands (axis, paddings, multiples, depth, shape, range
bounds) are *static*: they are concretized at trace time, mirroring
XLA's static-shape model, so they must not be produced by traced
computation.  Data operands are fully traceable.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, next_rng_key

__all__ = [
    "Operation", "All", "Any", "ArgMax", "BatchMatMul", "Cast", "Ceil",
    "Equal", "NotEqual", "Greater", "GreaterEqual", "Less", "LessEqual",
    "Erf", "Erfc", "Expm1", "Floor", "FloorDiv", "FloorMod", "Inv",
    "IsFinite", "IsInf", "IsNan", "L2Loss", "Lgamma", "Digamma", "Log1p",
    "LogicalAnd", "LogicalOr", "LogicalNot", "MaximumOp", "MinimumOp",
    "Mod", "OneHot", "Pad", "Pow", "Prod", "RandomUniform", "RangeOps",
    "Rank", "Rint", "Round", "Rsqrt", "SelectOp", "Sign", "Slice",
    "SquaredDifference", "SumOp", "TileOp", "TopK", "TruncateDiv",
    "TruncatedNormal", "BucketizedCol", "CrossEntropy", "DepthwiseConv2D",
    "TensorOp",
]


class Operation(Module):
    """Stateless forward-only op (≙ nn/ops/Operation.scala: backward is
    an error; here gradients simply flow through jax where defined)."""


class _Unary(Operation):
    fn = None

    def forward(self, x):
        return type(self).fn(x)


class _Binary(Operation):
    """Takes a table (pair) input like the reference ops."""
    fn = None

    def forward(self, xs):
        a, b = xs
        return type(self).fn(a, b)


class _AxisReduce(Operation):
    """Reduce over an `axis` table input (shared by All/Any)."""

    fn = None

    def __init__(self, keep_dims: bool = False):
        super().__init__()
        self.keep_dims = keep_dims

    def forward(self, xs):
        x, axis = (xs if isinstance(xs, (tuple, list)) else (xs, None))
        axis = tuple(np.asarray(axis).ravel().tolist()) \
            if axis is not None else None
        return type(self).fn(x, axis=axis, keepdims=self.keep_dims)


class All(_AxisReduce):
    """(≙ nn/ops/All.scala)"""
    fn = staticmethod(jnp.all)


class Any(_AxisReduce):
    """(≙ nn/ops/Any.scala)"""
    fn = staticmethod(jnp.any)


class ArgMax(Operation):
    def __init__(self, axis: int = 0):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return jnp.argmax(x, axis=self.axis)


class BatchMatMul(Operation):
    """(≙ nn/ops/BatchMatMul.scala) with adj_x/adj_y transposes."""

    def __init__(self, adj_x: bool = False, adj_y: bool = False):
        super().__init__()
        self.adj_x, self.adj_y = adj_x, adj_y

    def forward(self, xs):
        a, b = xs
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


class Cast(Operation):
    def __init__(self, dtype):
        super().__init__()
        self.dtype = jnp.dtype(dtype)

    def forward(self, x):
        return x.astype(self.dtype)


class Ceil(_Unary):
    fn = staticmethod(jnp.ceil)


class Equal(_Binary):
    fn = staticmethod(jnp.equal)


class NotEqual(_Binary):
    fn = staticmethod(jnp.not_equal)


class Greater(_Binary):
    fn = staticmethod(jnp.greater)


class GreaterEqual(_Binary):
    fn = staticmethod(jnp.greater_equal)


class Less(_Binary):
    fn = staticmethod(jnp.less)


class LessEqual(_Binary):
    fn = staticmethod(jnp.less_equal)


class Erf(_Unary):
    fn = staticmethod(jax.scipy.special.erf)


class Erfc(_Unary):
    fn = staticmethod(jax.scipy.special.erfc)


class Expm1(_Unary):
    fn = staticmethod(jnp.expm1)


class Floor(_Unary):
    fn = staticmethod(jnp.floor)


class FloorDiv(_Binary):
    fn = staticmethod(jnp.floor_divide)


class FloorMod(_Binary):
    fn = staticmethod(jnp.mod)


class Inv(_Unary):
    """Reciprocal (≙ nn/ops/Inv.scala)."""
    fn = staticmethod(lambda x: 1.0 / x)


class IsFinite(_Unary):
    fn = staticmethod(jnp.isfinite)


class IsInf(_Unary):
    fn = staticmethod(jnp.isinf)


class IsNan(_Unary):
    fn = staticmethod(jnp.isnan)


class L2Loss(Operation):
    """sum(x**2)/2 (≙ nn/ops/L2Loss.scala)."""

    def forward(self, x):
        return jnp.sum(jnp.square(x)) / 2


class Lgamma(_Unary):
    fn = staticmethod(jax.scipy.special.gammaln)


class Digamma(_Unary):
    fn = staticmethod(jax.scipy.special.digamma)


class Log1p(_Unary):
    fn = staticmethod(jnp.log1p)


class LogicalAnd(_Binary):
    fn = staticmethod(jnp.logical_and)


class LogicalOr(_Binary):
    fn = staticmethod(jnp.logical_or)


class LogicalNot(_Unary):
    fn = staticmethod(jnp.logical_not)


class MaximumOp(_Binary):
    fn = staticmethod(jnp.maximum)


class MinimumOp(_Binary):
    fn = staticmethod(jnp.minimum)


class Mod(_Binary):
    # C truncated-remainder semantics (pairs with TruncateDiv so that
    # truncatediv(x, y) * y + mod(x, y) == x, matching the TF op)
    fn = staticmethod(jax.lax.rem)


class OneHot(Operation):
    """(≙ nn/ops/OneHot.scala): table input (indices, depth, on, off)."""

    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, xs):
        indices, depth = xs[0], int(xs[1])
        on = xs[2] if len(xs) > 2 else 1.0
        off = xs[3] if len(xs) > 3 else 0.0
        oh = jax.nn.one_hot(indices, depth, axis=self.axis)
        return oh * on + (1 - oh) * off


class Pad(Operation):
    """(≙ nn/ops/Pad.scala): table input (tensor, paddings [n,2])."""

    def __init__(self, mode: str = "CONSTANT", constant_value: float = 0.0):
        super().__init__()
        if mode not in ("CONSTANT", "REFLECT", "SYMMETRIC"):
            raise ValueError(f"unsupported pad mode {mode!r}")
        self.mode = mode
        self.constant_value = constant_value

    def forward(self, xs):
        x, paddings = xs
        pads = [tuple(int(v) for v in row) for row in np.asarray(paddings)]
        if self.mode == "CONSTANT":
            return jnp.pad(x, pads, constant_values=self.constant_value)
        return jnp.pad(x, pads, mode=self.mode.lower())


class Pow(_Binary):
    fn = staticmethod(jnp.power)


class Prod(Operation):
    def __init__(self, axis: int = 0, keep_dims: bool = False):
        super().__init__()
        self.axis, self.keep_dims = axis, keep_dims

    def forward(self, x):
        return jnp.prod(x, axis=self.axis, keepdims=self.keep_dims)


class RandomUniform(Operation):
    """(≙ nn/ops/RandomUniform.scala). Needs forward_context rng."""

    def __init__(self, minval: float = 0.0, maxval: float = 1.0):
        super().__init__()
        self.minval, self.maxval = minval, maxval

    def forward(self, shape):
        shape = tuple(int(s) for s in np.asarray(shape).ravel())
        return jax.random.uniform(next_rng_key(), shape,
                                  minval=self.minval, maxval=self.maxval)


class RangeOps(Operation):
    """(≙ nn/ops/RangeOps.scala): (start, limit, delta) table; float
    ranges supported like tf.range."""

    def forward(self, xs):
        start, limit, delta = (np.asarray(v).item() for v in xs)
        return jnp.arange(start, limit, delta)


class Rank(Operation):
    def forward(self, x):
        return jnp.asarray(x.ndim, jnp.int32)


class Rint(_Unary):
    fn = staticmethod(jnp.rint)


class Round(_Unary):
    fn = staticmethod(jnp.round)


class Rsqrt(_Unary):
    fn = staticmethod(jax.lax.rsqrt)


class SelectOp(Operation):
    """tf.where(cond, x, y) (≙ nn/ops/Select.scala)."""

    def forward(self, xs):
        cond, x, y = xs
        return jnp.where(cond, x, y)


class Sign(_Unary):
    fn = staticmethod(jnp.sign)


class Slice(Operation):
    """(≙ nn/ops/Slice.scala): static begin/size config."""

    def __init__(self, begin: Sequence[int], size: Sequence[int]):
        super().__init__()
        self.begin = tuple(begin)
        self.size = tuple(size)

    def forward(self, x):
        limits = tuple(b + (s if s != -1 else dim - b)
                       for b, s, dim in zip(self.begin, self.size, x.shape))
        return jax.lax.slice(x, self.begin, limits)


class SquaredDifference(_Binary):
    fn = staticmethod(lambda a, b: jnp.square(a - b))


class SumOp(Operation):
    """reduce_sum with axis table input (≙ nn/ops/Sum.scala)."""

    def __init__(self, keep_dims: bool = False):
        super().__init__()
        self.keep_dims = keep_dims

    def forward(self, xs):
        x, axis = (xs if isinstance(xs, (tuple, list)) else (xs, None))
        axis = tuple(np.asarray(axis).ravel().tolist()) \
            if axis is not None else None
        return jnp.sum(x, axis=axis, keepdims=self.keep_dims)


class TileOp(Operation):
    """(≙ nn/ops/Tile.scala): (tensor, multiples) table."""

    def forward(self, xs):
        x, multiples = xs
        return jnp.tile(x, tuple(int(m) for m in np.asarray(multiples)))


class TopK(Operation):
    def __init__(self, k: int, sorted: bool = True):
        super().__init__()
        self.k = k
        # lax.top_k always returns sorted results; sorted=False (order
        # unspecified in the TF contract) is satisfied by that too.
        self.sorted = sorted

    def forward(self, x):
        values, indices = jax.lax.top_k(x, self.k)
        return values, indices


def _truncate_div(a, b):
    if jnp.issubdtype(jnp.result_type(a), jnp.integer):
        return jax.lax.div(a, b)  # exact C-style truncating int division
    return jnp.trunc(a / b)


class TruncateDiv(_Binary):
    fn = staticmethod(_truncate_div)


class TruncatedNormal(Operation):
    """(≙ nn/ops/TruncatedNormal.scala). Needs forward_context rng."""

    def __init__(self, mean: float = 0.0, stddev: float = 1.0):
        super().__init__()
        self.mean, self.stddev = mean, stddev

    def forward(self, shape):
        shape = tuple(int(s) for s in np.asarray(shape).ravel())
        z = jax.random.truncated_normal(next_rng_key(), -2.0, 2.0, shape)
        return z * self.stddev + self.mean


class BucketizedCol(Operation):
    """Bucketize by boundaries (≙ nn/ops/BucketizedCol.scala)."""

    def __init__(self, boundaries: Sequence[float]):
        super().__init__()
        self.boundaries = jnp.asarray(sorted(boundaries))

    def forward(self, x):
        return jnp.searchsorted(self.boundaries, x, side="right") \
            .astype(jnp.int32)


class CrossEntropy(Operation):
    """Per-sample softmax cross entropy from logits
    (≙ nn/ops/CrossEntropy.scala): input (logits, one-hot labels)."""

    def forward(self, xs):
        logits, labels = xs
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(labels * logp, axis=-1)


class DepthwiseConv2D(Operation):
    """(≙ nn/ops/DepthwiseConv2D.scala): input (x NHWC, filter HWCM)."""

    def __init__(self, stride_w: int = 1, stride_h: int = 1,
                 padding: str = "SAME"):
        super().__init__()
        self.strides = (stride_h, stride_w)
        self.padding = padding

    def forward(self, xs):
        x, w = xs
        kh, kw, c, m = w.shape
        w = w.reshape(kh, kw, 1, c * m)
        return jax.lax.conv_general_dilated(
            x, w, window_strides=self.strides, padding=self.padding,
            feature_group_count=c,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class TensorOp(Operation):
    """Composable tensor-function op (reference nn/ops/TensorOp.scala):
    arithmetic operators and chainable transform methods build a fused
    pointwise pipeline — ``(TensorOp() * 2.0 + 1.0).sqrt()`` is one op
    whose forward applies the whole chain (XLA fuses it for free).
    """

    def __init__(self, fn=None):
        super().__init__()
        self.fn = fn or (lambda x: x)

    def forward(self, x):
        return self.fn(x)

    # -- composition -------------------------------------------------------
    def then(self, g) -> "TensorOp":
        f = self.fn
        return TensorOp(lambda x: g(f(x)))

    def __add__(self, other):
        if isinstance(other, TensorOp):
            f, g = self.fn, other.fn
            return TensorOp(lambda x: f(x) + g(x))
        return self.then(lambda y: y + other)

    def __sub__(self, other):
        if isinstance(other, TensorOp):
            f, g = self.fn, other.fn
            return TensorOp(lambda x: f(x) - g(x))
        return self.then(lambda y: y - other)

    def __mul__(self, other):
        if isinstance(other, TensorOp):
            f, g = self.fn, other.fn
            return TensorOp(lambda x: f(x) * g(x))
        return self.then(lambda y: y * other)

    def __truediv__(self, other):
        if isinstance(other, TensorOp):
            f, g = self.fn, other.fn
            return TensorOp(lambda x: f(x) / g(x))
        return self.then(lambda y: y / other)

    def __pow__(self, p):
        return self.then(lambda y: y ** p)

    # -- chainable transforms (TensorOp.scala method set) -------------------
    def abs(self):
        return self.then(jnp.abs)

    def sqrt(self):
        return self.then(jnp.sqrt)

    def rsqrt(self):
        return self.then(jax.lax.rsqrt)

    def square(self):
        return self.then(jnp.square)

    def exp(self):
        return self.then(jnp.exp)

    def log(self):
        return self.then(jnp.log)

    def log1p(self):
        return self.then(jnp.log1p)

    def floor(self):
        return self.then(jnp.floor)

    def ceil(self):
        return self.then(jnp.ceil)

    def negative(self):
        return self.then(jnp.negative)

    def inv(self):
        return self.then(lambda y: 1.0 / y)

    def sigmoid(self):
        return self.then(jax.nn.sigmoid)

    def tanh(self):
        return self.then(jnp.tanh)

    def relu(self):
        return self.then(jax.nn.relu)

    def elu(self):
        return self.then(jax.nn.elu)

    def softmax(self):
        return self.then(lambda y: jax.nn.softmax(y, axis=-1))

    def softplus(self):
        return self.then(jax.nn.softplus)

    def softsign(self):
        return self.then(jax.nn.soft_sign)

    def clamp(self, lo, hi):
        return self.then(lambda y: jnp.clip(y, lo, hi))

    def sum(self, axis=None, keepdims=False):
        return self.then(lambda y: jnp.sum(y, axis=axis,
                                           keepdims=keepdims))

    def mean(self, axis=None, keepdims=False):
        return self.then(lambda y: jnp.mean(y, axis=axis,
                                            keepdims=keepdims))

    def t(self):
        return self.then(lambda y: jnp.swapaxes(y, -1, -2))


class ApproximateEqual(Operation):
    """|a - b| < tolerance (reference nn/ops/ApproximateEqual.scala)."""

    def __init__(self, tolerance: float = 1e-5):
        super().__init__()
        self.tolerance = float(tolerance)

    def forward(self, xs):
        a, b = xs
        return jnp.abs(a - b) < self.tolerance


class Gather(Operation):
    """Gather rows of params by indices along axis 0 (reference
    nn/ops/Gather.scala; TF Gather).  Indices are 0-based like TF."""

    def forward(self, xs):
        params, indices = xs
        # TF gather errors on out-of-bounds on CPU and zero-fills on
        # GPU; jnp.take's default silently CLAMPS (neither).  Zero-fill
        # (the TF-GPU behavior) is the XLA-friendly choice that never
        # returns a wrong-but-plausible row
        return jnp.take(params, jnp.asarray(indices, jnp.int32), axis=0,
                        mode="fill", fill_value=0)


class InTopK(Operation):
    """targets[i] in top-k of predictions[i] (reference
    nn/ops/InTopK.scala).  ``start_from_1``: 1-based target ids."""

    def __init__(self, k: int, start_from_1: bool = False):
        super().__init__()
        self.k = int(k)
        self.start_from_1 = start_from_1

    def forward(self, xs):
        predictions, targets = xs
        targets = jnp.asarray(targets, jnp.int32)
        if self.start_from_1:
            targets = targets - 1
        n_classes = predictions.shape[1]
        valid = (targets >= 0) & (targets < n_classes)
        safe = jnp.clip(targets, 0, n_classes - 1)
        target_score = jnp.take_along_axis(
            predictions, safe[:, None], axis=1)[:, 0]
        rank = jnp.sum(predictions > target_score[:, None], axis=1)
        # out-of-range targets and non-finite target predictions are
        # False, matching TF in_top_k (the gather's clamping must not
        # silently score another class, and NaN comparisons being False
        # must not count as rank 0)
        return valid & jnp.isfinite(target_score) & (rank < self.k)


class SegmentSum(Operation):
    """Sum rows sharing a segment id; ids must be sorted ascending
    (reference nn/ops/SegmentSum.scala; TF segment_sum).  Output has
    ``max(id)+1`` rows."""

    def __init__(self, num_segments=None):
        super().__init__()
        # static segment count keeps the op jit-traceable (shape must
        # be static under XLA); without it the count is read from the
        # ids EAGERLY, which only works outside jit
        self.num_segments = num_segments

    def forward(self, xs):
        data, segment_ids = xs
        segment_ids = jnp.asarray(segment_ids, jnp.int32)
        num = self.num_segments
        if num is None:
            if isinstance(segment_ids, jax.core.Tracer):
                raise ValueError(
                    "SegmentSum under jit needs a static segment count: "
                    "construct it as SegmentSum(num_segments=N) (the "
                    "output shape cannot depend on traced values)")
            num = int(np.asarray(segment_ids)[-1]) + 1 \
                if segment_ids.size else 0
        return jax.ops.segment_sum(data, segment_ids, num_segments=num)


class ModuleToOperation(Operation):
    """Use any Module as a forward-only op (reference
    nn/ops/ModuleToOperation.scala)."""

    def __init__(self, module: Module):
        super().__init__()
        self.module = module

    def forward(self, x):
        return self.module.forward(x)


class Dilation2D(Operation):
    """Grayscale morphological dilation: out[b,y,x,c] = max over the
    (dilated) window of input + filter (reference
    nn/ops/Dilation2D.scala; TF tf.nn.dilation2d).  NHWC input,
    [kh, kw, C] filter; strides/rates are the TF 4-element lists."""

    def __init__(self, strides, rates, padding: str = "VALID"):
        super().__init__()
        self.strides = tuple(strides)
        self.rates = tuple(rates)
        self.padding = padding.upper()

    def forward(self, xs):
        x, filt = xs
        kh, kw, c = filt.shape
        _, sh, sw, _ = self.strides
        _, rh, rw, _ = self.rates
        if self.padding == "SAME":
            # TF treats padded elements as -inf (they must never win
            # the max); patches would zero-fill, so pad explicitly
            eff_h, eff_w = (kh - 1) * rh + 1, (kw - 1) * rw + 1
            H, W = x.shape[1], x.shape[2]
            ph = max((-(-H // sh) - 1) * sh + eff_h - H, 0)
            pw = max((-(-W // sw) - 1) * sw + eff_w - W, 0)
            # patches extract via a conv (0 x -inf = NaN), so pad
            # with a huge finite negative instead of -inf
            neg = (jnp.iinfo(x.dtype).min // 2
                   if jnp.issubdtype(x.dtype, jnp.integer)
                   else jnp.finfo(x.dtype).min / 2)
            x = jnp.pad(x, ((0, 0), (ph // 2, ph - ph // 2),
                            (pw // 2, pw - pw // 2), (0, 0)),
                        constant_values=neg)
        # patches: [B, H', W', C*kh*kw] in (c, kh, kw) minor order
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), "VALID",
            rhs_dilation=(rh, rw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        b, oh, ow, _ = patches.shape
        patches = patches.reshape(b, oh, ow, c, kh * kw)
        filt_flat = jnp.transpose(filt, (2, 0, 1)).reshape(c, kh * kw)
        return jnp.max(patches + filt_flat, axis=-1)


class Substr(Operation):
    """Substring over byte-string arrays (reference nn/ops/Substr.scala;
    TF Substr).  Host-side op: inputs are numpy object/bytes arrays,
    (pos, len) scalars."""

    def forward(self, xs):
        strings, pos, length = xs
        pos, length = int(pos), int(length)
        arr = np.asarray(strings, dtype=object)

        def sub(s):
            if pos < 0 or pos > len(s):
                # TF Substr raises InvalidArgumentError here; silently
                # returning b'' would hide the bad offset
                raise ValueError(
                    f"Substr pos {pos} out of range for input of "
                    f"length {len(s)}")
            return s[pos:pos + length]

        if arr.shape == ():
            return sub(arr[()])
        out = np.empty(arr.shape, dtype=object)
        for idx in np.ndindex(arr.shape):
            out[idx] = sub(arr[idx])
        return out


# reference nn/ops names whose natural spelling clashed with jnp
# builtins when these ops were first written
Maximum = MaximumOp
Minimum = MinimumOp
# reference nn/ops/Compare.scala: the abstract base of the comparison
# ops (Greater/Less/... extend it) — our _Binary plays that role
Compare = _Binary

__all__ += ["ApproximateEqual", "Gather", "InTopK", "SegmentSum",
            "ModuleToOperation", "Dilation2D", "Substr", "Maximum",
            "Minimum", "Compare"]
